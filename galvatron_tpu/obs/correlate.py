"""Cross-process trace correlation: trace ids + multi-dump timeline merge.

PR 6's tracer is strictly per-process: each router/replica/supervisor owns
a ring, dumps its own ``flight_<ts>_<pid>.json``, and exports its own
Perfetto file — so a request that fails over router→replica-A→replica-B
tells its story in three files with three unrelated clocks. This module is
the correlation layer:

- **trace ids** (:func:`mint_trace_id` / :data:`TRACE_HEADER`): the fleet
  router mints one id per admitted request — only when tracing is armed;
  tracing off adds zero work and no header — and propagates it via the
  ``X-Galvatron-Trace-Id`` HTTP header. The replica threads it through
  scheduler → prefill span → every lifecycle instant, so one grep (or one
  Perfetto query on ``args.trace_id``) follows the request across every
  process it touched.

- **merge export** (:func:`merge_flight_dumps`, ``cli trace-export
  --merge DIR``): fuse every flight dump under a directory into ONE
  Chrome-trace document. Each dump becomes its own pid track group
  (Perfetto renders per-process lanes); timestamps are aligned onto a
  shared clock using each dump's ``epoch_wall`` anchor — every tracer
  stamps spans with a *monotonic* clock whose zero point it records in
  wall time, so ``offset_us = (epoch_wall - min(epoch_wall)) * 1e6``
  places all processes on the earliest dump's timeline. Wall-clock anchors
  are NTP-grade, not perf-counter-grade: good to ~ms on one host, which is
  exactly what "see the failover hop on one screen" needs.

Torn dumps (a process crashed mid-write before the atomic rename, or an
operator copied a partial file) are SKIPPED with a line-numbered warning —
the same contract as ``read_metrics``' torn-tail handling: forensics tools
must degrade, never refuse, on the exact artifacts crashes produce.
"""

from __future__ import annotations

import glob
import json
import os
import re
import uuid
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from galvatron_tpu.obs.flight import FLIGHT_SCHEMA
from galvatron_tpu.obs.tracing import chrome_trace

#: the propagation header: router → replica. Mint/attach ONLY when tracing
#: is enabled — with tracing off the header must be absent (pinned by test).
TRACE_HEADER = "X-Galvatron-Trace-Id"

_PID_FROM_NAME = re.compile(r"flight_\d{8}_\d{6}_(\d+)\.json$")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (uuid4-derived: no coordination, no
    clock reads beyond what uuid already does)."""
    return uuid.uuid4().hex[:16]


def load_dump(path: str) -> Optional[Dict[str, Any]]:
    """Read one flight dump; returns None (with a warning naming the file
    and the torn line/column) instead of raising on a torn/partial file.
    A well-formed JSON document that is not a flight dump also warns —
    a merge directory may hold unrelated .json files."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        warnings.warn(f"{path}: unreadable flight dump, skipping: {e}")
        return None
    except ValueError as e:
        lineno = getattr(e, "lineno", "?")
        warnings.warn(
            f"{path}: torn/partial flight dump (crash mid-write?), "
            f"skipping — JSON parse failed at line {lineno}: {e}"
        )
        return None
    if not isinstance(doc, dict) or doc.get("schema") != FLIGHT_SCHEMA:
        warnings.warn(f"{path}: not a {FLIGHT_SCHEMA} dump, skipping")
        return None
    return doc


def dump_pid(doc: Dict[str, Any], path: str, fallback: int) -> int:
    """The pid that keys this dump's track group: the dump's own ``pid``
    field (new dumps), the filename's trailing ``_<pid>`` (old dumps), or a
    synthetic fallback index (merge must not collapse two dumps onto one
    track group just because provenance is missing)."""
    pid = doc.get("pid")
    if isinstance(pid, int):
        return pid
    m = _PID_FROM_NAME.search(os.path.basename(path))
    if m:
        return int(m.group(1))
    return fallback


def find_dumps(root: str) -> List[str]:
    """Every ``flight_*.json`` under ``root``, recursively, sorted — the
    fleet writes per-process dumps into per-replica subdirectories."""
    pats = [os.path.join(root, "flight_*.json"),
            os.path.join(root, "**", "flight_*.json")]
    out: List[str] = []
    seen = set()
    for p in pats:
        for path in glob.glob(p, recursive=True):
            if path not in seen:
                seen.add(path)
                out.append(path)
    return sorted(out)


def merge_flight_dumps(
    paths: Sequence[str],
    process_names: Optional[Dict[str, str]] = None,
) -> Tuple[Dict[str, Any], List[str]]:
    """Fuse flight dumps into one Chrome-trace document.

    Returns ``(doc, used_paths)``; torn/foreign files are skipped with a
    warning and excluded from ``used_paths``. Raises ValueError only when
    NO dump survives — an empty merge is an operator error worth a loud rc.

    Clock alignment: each tracer's span timestamps are microseconds since
    its own monotonic epoch; the dump records that epoch's wall time
    (``epoch_wall``). The earliest epoch becomes ts=0 of the merged
    timeline and every other dump shifts right by its wall-clock delta.
    """
    docs: List[Tuple[str, Dict[str, Any]]] = []
    for p in paths:
        doc = load_dump(p)
        if doc is not None:
            docs.append((p, doc))
    if not docs:
        raise ValueError(
            f"no readable flight dumps among {len(paths)} file(s)"
        )
    ref = min(float(d.get("epoch_wall", d.get("wall_time", 0.0)))
              for _, d in docs)
    events: List[Dict[str, Any]] = []
    used: List[str] = []
    for i, (path, doc) in enumerate(docs):
        epoch = float(doc.get("epoch_wall", doc.get("wall_time", ref)))
        offset_us = (epoch - ref) * 1e6
        pid = dump_pid(doc, path, fallback=100_000 + i)
        name = None
        if process_names:
            name = process_names.get(path)
        if not name:
            reason = str(doc.get("reason", ""))[:60]
            name = f"pid {pid}" + (f" — {reason}" if reason else "")
        sub = chrome_trace(
            doc.get("spans", []), pid=pid, ts_offset_us=offset_us,
            process_name=name,
        )
        events.extend(sub["traceEvents"])
        used.append(path)
    return {"traceEvents": events, "displayTimeUnit": "ms"}, used


def merge_directory(root: str, out_path: Optional[str] = None) -> Tuple[str, List[str]]:
    """``cli trace-export --merge DIR`` backend: find, merge, write.
    Returns ``(output_path, used_paths)``. Raises ValueError when the
    directory holds no usable dump."""
    paths = find_dumps(root)
    if not paths:
        raise ValueError(f"{root}: no flight_*.json dumps found")
    doc, used = merge_flight_dumps(paths)
    out = out_path or os.path.join(root, "merged.trace.json")
    d = os.path.dirname(os.path.abspath(out))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out, "w") as f:
        json.dump(doc, f)
    return out, used


def trace_ids_in(doc: Dict[str, Any]) -> Dict[str, List[int]]:
    """``trace_id → sorted pids it appears on`` for a merged document —
    the assertion the chaos harness makes ("this id hopped 3 processes")."""
    out: Dict[str, set] = {}
    for ev in doc.get("traceEvents", []):
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            out.setdefault(tid, set()).add(int(ev.get("pid", 0)))
    return {k: sorted(v) for k, v in out.items()}
