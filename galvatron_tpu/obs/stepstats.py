"""Step accounting: model-FLOPs estimate, tokens/s, achieved TFLOP/s, MFU.

The throughput number alone ("N tokens/s") says nothing about how far from
the hardware ceiling a run sits; MegaScale (NSDI '24) and PaLM report MFU —
model FLOPs per second over the accelerators' peak — as the comparable
utilization metric. This module derives the FLOPs side from ``ModelConfig``
analytically (attention projections + attention core + MLP + vocab head),
so every ``train_iter`` JSONL record and ``RuntimeProfiler`` summary can
carry ``tokens_per_s`` / ``tflops`` / ``mfu`` with no extra measurement.

Two FLOPs totals, following the PaLM convention:

- **model FLOPs** (feeds MFU): fwd + 2x fwd backward, NO recompute — MFU is
  a property of the model and the wall clock, unchanged by checkpointing.
- **hardware FLOPs** (feeds HFU): adds the rematerialized compute — full
  forward per full-ckpt layer, the attention core per selective-ckpt layer,
  the MLP branch when ``mlp_recompute`` is ``gate``/``policy`` (PR 3's
  policy replays the gate product + fp32 norm statistics in backward).

Attention-core FLOPs use the full ``s x s`` matmul pair (no causal-mask
discount), matching Megatron's accounting. MoE layers are priced at the
dense per-token cost of one expert (top-1 switch routing); router compute
is ignored.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax

from galvatron_tpu.models.modeling import ModelConfig

# per-device peak dense bf16 TFLOP/s by TPU generation (published peaks;
# keyed by substring of device_kind). Override: GALVATRON_PEAK_TFLOPS.
_PEAK_TFLOPS_BY_KIND = (
    ("v6", 918.0),  # Trillium
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_flops_per_device(override_tflops: float = 0.0) -> Optional[float]:
    """Peak dense FLOP/s of one local device, or None when unknown (CPU,
    unrecognized kind). ``override_tflops`` (or GALVATRON_PEAK_TFLOPS) wins —
    quoting a wrong peak would make every MFU number silently wrong."""
    if override_tflops:
        return float(override_tflops) * 1e12
    env = os.environ.get("GALVATRON_PEAK_TFLOPS", "")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            pass
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for key, tf in _PEAK_TFLOPS_BY_KIND:
        if key in kind:
            return tf * 1e12
    return None


def attn_proj_flops_per_token(cfg: ModelConfig) -> float:
    """QKV + output projection matmul FLOPs for one token, one layer."""
    h, hd = cfg.hidden_size, cfg.head_dim
    qkv_cols = h + 2 * cfg.kv_heads * hd  # q at h, k/v at kv_heads*hd (GQA)
    return 2.0 * h * qkv_cols + 2.0 * h * h


def attn_core_flops_per_token(cfg: ModelConfig, seq_len: int) -> float:
    """q@k^T and p@v for one token against ``seq_len`` keys (full square,
    no causal discount — Megatron's convention)."""
    return 2.0 * 2.0 * seq_len * cfg.hidden_size


def mlp_flops_per_token(cfg: ModelConfig) -> float:
    n_gemm = 3 if cfg.act_fn == "swiglu" else 2  # gate+up+down vs up+down
    return 2.0 * n_gemm * cfg.hidden_size * cfg.ffn


def layer_fwd_flops_per_token(cfg: ModelConfig, seq_len: int) -> float:
    """Forward FLOPs of one transformer layer for one token."""
    return (
        attn_proj_flops_per_token(cfg)
        + attn_core_flops_per_token(cfg, seq_len)
        + mlp_flops_per_token(cfg)
    )


def head_flops_per_loss_token(cfg: ModelConfig) -> float:
    return 2.0 * cfg.hidden_size * cfg.vocab_size


def _remat_fwd_flops_per_token(cfg: ModelConfig, seq_len: int, hp=None) -> float:
    """Extra forward compute replayed in backward, per token summed over all
    layers (the hardware-FLOPs delta). Per-layer when strategies are known;
    the uniform ``cfg.mlp_recompute`` rule otherwise."""
    strategies = list(getattr(hp, "layer_strategies", None) or [])
    if not strategies:
        class _Uniform:  # cfg-only callers: one pseudo-strategy per layer
            ckpt = 0
        strategies = [_Uniform()] * cfg.total_layers
    total = 0.0
    for s in strategies:
        ckpt = getattr(s, "ckpt", 0)
        if ckpt in (1, "full"):
            total += layer_fwd_flops_per_token(cfg, seq_len)
        elif ckpt in (2, "selective"):
            total += attn_core_flops_per_token(cfg, seq_len)
        elif cfg.mlp_recompute != "off":
            # PR 3 policy/gate: the activation product (and fp32 norm stats,
            # negligible next to the GEMMs) replays once per layer
            total += mlp_flops_per_token(cfg)
    return total


@dataclass
class StepStats:
    """Precomputed per-step FLOPs for one (model, strategy, batch) shape;
    ``per_iter(iter_ms)`` turns a measured step time into the JSONL fields."""

    cfg: ModelConfig
    global_bsz: int
    seq_len: int
    hp: Any = None  # HybridParallelConfig (per-layer remat awareness) or None
    num_devices: int = 0
    peak_tflops_override: float = 0.0

    def __post_init__(self):
        if not self.num_devices:
            self.num_devices = jax.device_count()
        cfg, seq = self.cfg, self.seq_len
        tokens = float(self.global_bsz) * seq
        from galvatron_tpu.models.modeling import loss_tokens_per_sample

        loss_tokens = float(self.global_bsz) * loss_tokens_per_sample(cfg, seq)
        fwd = (
            tokens * cfg.total_layers * layer_fwd_flops_per_token(cfg, seq)
            + loss_tokens * head_flops_per_loss_token(cfg)
        )
        self.model_flops_per_step = 3.0 * fwd  # fwd + 2x fwd backward
        self.hardware_flops_per_step = self.model_flops_per_step + (
            tokens * _remat_fwd_flops_per_token(cfg, seq, self.hp)
        )
        self.tokens_per_step = tokens
        self._peak = peak_flops_per_device(self.peak_tflops_override)

    @property
    def peak_flops_per_device(self) -> Optional[float]:
        return self._peak

    def per_iter(
        self,
        iter_ms: Optional[float],
        global_bsz: Optional[float] = None,
        nonpad_tokens: Optional[float] = None,
    ) -> Dict[str, Optional[float]]:
        """tokens/s, achieved model TFLOP/s (per device), MFU and HFU for one
        measured iteration. ``global_bsz`` rescales the precomputed step
        FLOPs/tokens linearly (batch-size rampup runs at smaller sizes).
        MFU/HFU are None when the device peak is unknown (CPU sim) — a
        made-up denominator would be worse than no number.

        ``nonpad_tokens`` (packed sequences): the batch's real-token count.
        ``tokens_per_s`` and MFU/HFU then count NON-PAD tokens only — padded
        positions burn FLOPs but are not useful work, and counting them made
        MFU silently overstate utilization exactly when packing was off. The
        raw (pad-inclusive) rate stays available as ``tokens_per_s_raw`` so
        pre-packing dashboards keep their meaning, and the ratio is exposed
        as ``packing_efficiency``."""
        if not iter_ms or iter_ms <= 0:
            out: Dict[str, Optional[float]] = {
                "tokens_per_s": None, "tflops_per_device": None,
                "mfu": None, "hfu": None,
                "comm_wait_ms": None, "bubble_fraction": None,
            }
            if nonpad_tokens is not None:
                out["tokens_per_s_raw"] = None
                out["packing_efficiency"] = None
            return out
        scale = (global_bsz / self.global_bsz) if global_bsz else 1.0
        s = iter_ms / 1000.0
        raw_tokens = scale * self.tokens_per_step
        useful_frac = 1.0
        if nonpad_tokens is not None and raw_tokens > 0:
            useful_frac = min(1.0, float(nonpad_tokens) / raw_tokens)
        flops_rate = useful_frac * scale * self.model_flops_per_step / s
        out = {
            "tokens_per_s": round(useful_frac * raw_tokens / s, 3),
            "tflops_per_device": round(flops_rate / self.num_devices / 1e12, 4),
            "mfu": None,
            "hfu": None,
        }
        if nonpad_tokens is not None:
            out["tokens_per_s_raw"] = round(raw_tokens / s, 3)
            out["packing_efficiency"] = round(useful_frac, 6)
        # comm-wait / bubble accounting (DESIGN.md "Overlap"): the host tracer
        # cannot see device-side collective stalls, so the aggregate is
        # derived — ideal_ms is the step's hardware-FLOPs time at peak, and
        # everything above it is non-compute (collective exposure, launch
        # gaps, stragglers). Absolute values lean on the analytic FLOPs model;
        # what the overlap work reads is the paired on/off DELTA on a fixed
        # shape, where the model error cancels. None on unknown peaks (CPU).
        out["comm_wait_ms"] = None
        out["bubble_fraction"] = None
        if self._peak:
            denom = self._peak * self.num_devices
            out["mfu"] = round(flops_rate / denom, 6)
            out["hfu"] = round(
                useful_frac * scale * self.hardware_flops_per_step / s / denom, 6
            )
            ideal_ms = scale * self.hardware_flops_per_step / denom * 1000.0
            out["comm_wait_ms"] = round(max(0.0, iter_ms - ideal_ms), 3)
            out["bubble_fraction"] = round(
                max(0.0, 1.0 - ideal_ms / iter_ms), 6
            )
        return out


def hbm_gauges() -> Dict[str, float]:
    """Per-device HBM gauges (bytes) where the backend reports them — the
    Prometheus-facing twin of RuntimeProfiler.memory_stats (MB)."""
    out: Dict[str, float] = {}
    for d in jax.devices():
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if st:
            out[f"dev{d.id}_bytes_in_use"] = float(st.get("bytes_in_use", 0))
            out[f"dev{d.id}_peak_bytes"] = float(st.get("peak_bytes_in_use", 0))
    return out
