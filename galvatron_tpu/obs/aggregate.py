"""Prometheus exposition parsing, fleet roll-up, and lint.

Three consumers share the text-format knowledge in this module so it lives
in exactly one place:

- ``parse_exposition``: the v0.0.4 text format back into families/samples —
  what the roll-up, the lint, and the tests all read.
- ``merge_expositions``: roll N replica expositions into one document with
  a ``replica`` label on every sample plus ``_fleet`` sum families for
  counters and histograms (bucket counts add; quantile gauges don't and are
  deliberately NOT summed). The fleet router's /metrics does its roll-up
  from replica /healthz JSON (cheaper, already probed); this text-level
  merge exists for offline aggregation of scraped files and as the
  reference semantics the router's roll-up is tested against.
- ``exposition_lint``: the CI gate (``python -m galvatron_tpu.obs.aggregate
  lint URL_OR_FILE ...``) — one HELP/TYPE per family, valid names/labels/
  escapes, histogram bucket monotonicity ending at ``+Inf`` with
  ``_count`` == the ``+Inf`` bucket. A malformed family silently breaks
  the WHOLE scrape for real collectors, so CI fails loudly instead.
"""

from __future__ import annotations

import re
import sys
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$"
)
_LABEL_PAIR_RE = re.compile(
    r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"'
)

#: histogram/summary suffixes that belong to the base family name
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


class Sample:
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str], value: float):
        self.name = name
        self.labels = labels
        self.value = value

    def __repr__(self):
        return f"Sample({self.name}, {self.labels}, {self.value})"


class Family:
    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str = "untyped", help_: str = ""):
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.samples: List[Sample] = []


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)  # "NaN" parses to nan


def base_family(sample_name: str, typed: Dict[str, str]) -> str:
    """Map a sample name to its family: histogram/summary samples carry
    ``_bucket``/``_sum``/``_count`` suffixes on the declared family name."""
    for suf in _FAMILY_SUFFIXES:
        if sample_name.endswith(suf):
            base = sample_name[: -len(suf)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return sample_name


def parse_exposition(text: str) -> Dict[str, Family]:
    """Parse v0.0.4 text into ``{family_name: Family}`` (insertion-ordered).
    Raises ValueError on a line that is neither comment, blank, nor valid
    sample — parse errors ARE lint errors."""
    families: Dict[str, Family] = {}
    typed: Dict[str, str] = {}

    def fam(name: str) -> Family:
        if name not in families:
            families[name] = Family(name, typed.get(name, "untyped"))
        return families[name]

    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise ValueError(f"line {i}: malformed TYPE comment: {line!r}")
            name, mtype = parts[2], parts[3].strip()
            typed[name] = mtype
            fam(name).mtype = mtype
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                raise ValueError(f"line {i}: malformed HELP comment: {line!r}")
            name = parts[2]
            fam(name).help = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: unparseable sample: {line!r}")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw is not None:
            consumed = 0
            for lm in _LABEL_PAIR_RE.finditer(raw):
                labels[lm.group("k")] = _unescape(lm.group("v"))
                consumed = lm.end()
            leftover = raw[consumed:].strip().strip(",")
            if leftover:
                raise ValueError(
                    f"line {i}: malformed label section {raw!r}"
                )
        name = m.group("name")
        try:
            value = _parse_value(m.group("value"))
        except ValueError as e:
            raise ValueError(f"line {i}: bad sample value: {line!r}") from e
        fam(base_family(name, typed)).samples.append(
            Sample(name, labels, value)
        )
    return families


# ---------------------------------------------------------------------------
# roll-up
# ---------------------------------------------------------------------------


def merge_expositions(
    texts: Dict[str, str], label: str = "replica"
) -> str:
    """Merge per-replica exposition texts into one document.

    ``texts`` maps a replica key (e.g. ``"0"``) to its /metrics body. Every
    sample is re-emitted with ``{label}="<key>"`` added; counter and
    histogram families additionally get an unlabeled ``_fleet`` sum family
    (bucket counts sum per ``le``). Gauges are labeled but not summed —
    a sum of occupancies is meaningful, a sum of p95s is not, and the
    caller can always ``sum by ()`` the labeled gauges it trusts.
    """
    from galvatron_tpu.obs.prom import PromText

    out = PromText(prefix="")
    merged: Dict[str, List[Tuple[str, Family]]] = {}
    order: List[str] = []
    for key, text in texts.items():
        for name, f in parse_exposition(text).items():
            if name not in merged:
                merged[name] = []
                order.append(name)
            merged[name].append((key, f))
    for name in order:
        variants = merged[name]
        mtype = variants[0][1].mtype
        help_ = next((f.help for _, f in variants if f.help), "")
        if mtype == "histogram":
            from galvatron_tpu.utils.metrics import Histogram

            snaps = []
            for key, f in variants:
                snap = _exposition_histogram_snapshot(f)
                if snap is None:
                    continue
                out.add_histogram(name, snap, labels={label: key},
                                  help_=help_)
                snaps.append(snap)
            if snaps:
                out.add_histogram(f"{name}_fleet",
                                  Histogram.merge_snapshots(snaps))
            continue
        totals: Dict[Tuple[Tuple[str, str], ...], float] = {}
        for key, f in variants:
            for s in f.samples:
                out.add(s.name, s.value, labels={**s.labels, label: key},
                        mtype=mtype, help_=help_)
                if mtype == "counter":
                    lk = tuple(sorted(s.labels.items()))
                    totals[lk] = totals.get(lk, 0.0) + s.value
        if mtype == "counter":
            for lk, v in totals.items():
                out.add(f"{name}_fleet", v, labels=dict(lk) or None,
                        mtype="counter")
    return out.render()


def _exposition_histogram_snapshot(f: Family) -> Optional[Dict[str, Any]]:
    """A parsed histogram family back into the ``Histogram.snapshot()``
    shape (single-series families only — labeled sub-series would need a
    per-series split the fleet roll-up doesn't produce)."""
    buckets: Dict[str, int] = {}
    total = None
    s = None
    for smp in f.samples:
        if smp.name.endswith("_bucket"):
            le = smp.labels.get("le")
            if le is None:
                return None
            key = "+Inf" if le == "+Inf" else repr(float(le))
            buckets[key] = int(smp.value)
        elif smp.name.endswith("_sum"):
            s = smp.value
        elif smp.name.endswith("_count"):
            total = int(smp.value)
    if not buckets or total is None or s is None:
        return None
    return {"sum": s, "count": total, "buckets": buckets}


# ---------------------------------------------------------------------------
# lint
# ---------------------------------------------------------------------------


def exposition_lint(text: str) -> List[str]:
    """Validate an exposition; returns a list of human-readable errors
    (empty = clean). Checks: parseability, HELP/TYPE at most once per
    family and before its samples, metric/label name syntax, duplicate
    series, histogram bucket monotonicity ending at ``+Inf`` with
    ``_count`` equal to it."""
    errors: List[str] = []
    try:
        families = parse_exposition(text)
    except ValueError as e:
        errors.append(str(e))
        return errors
    typed: Dict[str, str] = {f.name: f.mtype for f in families.values()}
    help_seen: Dict[str, int] = {}
    type_seen: Dict[str, int] = {}
    sampled: Dict[str, int] = {}  # family → first sample line
    seen_keys: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        header = False
        for kind, store in (("HELP", help_seen), ("TYPE", type_seen)):
            if line.startswith(f"# {kind} "):
                header = True
                parts = line.split(None, 3)
                name = parts[2] if len(parts) > 2 else ""
                if name in store:
                    errors.append(
                        f"line {i}: second {kind} for family {name!r} "
                        f"(first at line {store[name]})"
                    )
                else:
                    store[name] = i
                if name in sampled:
                    errors.append(
                        f"line {i}: {kind} for {name!r} appears after its "
                        f"samples (line {sampled[name]})"
                    )
        if header or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        sampled.setdefault(base_family(m.group("name"), typed), i)
        raw = m.group("labels")
        labels = tuple(sorted(
            (lm.group("k"), lm.group("v"))
            for lm in _LABEL_PAIR_RE.finditer(raw or "")
        ))
        key = (m.group("name"), labels)
        if key in seen_keys:
            errors.append(
                f"line {i}: duplicate series {m.group('name')}"
                f"{dict(labels)} (first at line {seen_keys[key]})"
            )
        else:
            seen_keys[key] = i
    for name, f in families.items():
        if not _NAME_RE.match(name):
            errors.append(f"invalid family name {name!r}")
        for s in f.samples:
            for k in s.labels:
                if not _LABEL_RE.match(k):
                    errors.append(
                        f"family {name!r}: invalid label name {k!r}"
                    )
        if f.mtype == "histogram":
            errors.extend(_lint_histogram(f))
    return errors


def _lint_histogram(f: Family) -> List[str]:
    """Bucket checks per labeled sub-series (grouped on the non-``le``
    labels): cumulative counts non-decreasing with ``le``, ``+Inf`` bucket
    present, ``_count`` == ``+Inf`` bucket."""
    errors: List[str] = []
    series: Dict[Tuple[Tuple[str, str], ...], Dict[str, float]] = {}
    counts: Dict[Tuple[Tuple[str, str], ...], float] = {}
    for s in f.samples:
        if s.name.endswith("_bucket"):
            le = s.labels.get("le")
            if le is None:
                errors.append(f"{s.name}: bucket sample missing le label")
                continue
            key = tuple(sorted(
                (k, v) for k, v in s.labels.items() if k != "le"
            ))
            series.setdefault(key, {})[le] = s.value
        elif s.name.endswith("_count"):
            key = tuple(sorted(s.labels.items()))
            counts[key] = s.value
    for key, buckets in series.items():
        where = f"{f.name}{dict(key) or ''}"
        if "+Inf" not in buckets:
            errors.append(f"{where}: histogram missing le=\"+Inf\" bucket")
        finite = sorted(
            ((float(le), v) for le, v in buckets.items() if le != "+Inf")
        )
        prev = 0.0
        for le, v in finite:
            if v < prev:
                errors.append(
                    f"{where}: bucket counts not monotone at le={le} "
                    f"({v} < {prev})"
                )
            prev = v
        inf = buckets.get("+Inf")
        if inf is not None and inf < prev:
            errors.append(
                f"{where}: +Inf bucket {inf} below last finite bucket {prev}"
            )
        if key in counts and inf is not None and counts[key] != inf:
            errors.append(
                f"{where}: _count {counts[key]} != +Inf bucket {inf}"
            )
    return errors


# ---------------------------------------------------------------------------
# CLI: python -m galvatron_tpu.obs.aggregate lint URL_OR_FILE ...
# ---------------------------------------------------------------------------


def _fetch(target: str) -> str:
    if target.startswith(("http://", "https://")):
        with urllib.request.urlopen(target, timeout=10) as resp:
            return resp.read().decode()
    with open(target) as f:
        return f.read()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] != "lint" or len(argv) < 2:
        print("usage: python -m galvatron_tpu.obs.aggregate lint "
              "<url-or-file> [...]", file=sys.stderr)
        return 2
    rc = 0
    for target in argv[1:]:
        try:
            text = _fetch(target)
        except OSError as e:
            print(f"{target}: fetch failed: {e}", file=sys.stderr)
            rc = 1
            continue
        errs = exposition_lint(text)
        if errs:
            rc = 1
            for e in errs:
                print(f"{target}: {e}", file=sys.stderr)
        else:
            n = len(parse_exposition(text))
            print(f"{target}: OK ({n} families)")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
