"""Prometheus text exposition (v0.0.4) + the headless-trainer sidecar.

Renders the stats the system already keeps — ``utils.metrics.Counters``,
``QuantileWindow`` readouts, arbitrary gauges — in the Prometheus text
format, so a scraper pointed at ``GET /metrics`` (served by ``server.py``
next to ``/healthz``, or by the ``--obs_port`` sidecar on a headless
training run) gets standard, labeled families instead of bespoke JSON.

Metric names (DESIGN.md § Observability has the full table):

  galvatron_server_requests_total{outcome=...}     server request counters
  galvatron_serving_*_total                        engine counters
  galvatron_serving_ttft_seconds{quantile=...}     TTFT readout
  galvatron_serving_{queue_depth,active_slots,occupancy,tokens_per_s}
  galvatron_train_*                                trainer sidecar gauges
  galvatron_hbm_bytes{device=...,kind=...}         HBM gauges
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _fmt_value(v: Any) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class PromText:
    """Accumulates samples; emits one ``# HELP``/``# TYPE`` header per family
    (first add wins) and validates names — a malformed family would make the
    whole scrape unparseable."""

    def __init__(self, prefix: str = "galvatron_"):
        self.prefix = prefix
        self._lines: list = []
        self._declared: set = set()

    def add(self, name: str, value: Any, *, labels: Optional[Dict[str, Any]] = None,
            mtype: str = "gauge", help_: str = "") -> None:
        fv = _fmt_value(value)
        if fv is None:
            return
        full = self.prefix + name
        if not _NAME_RE.match(full):
            raise ValueError(f"invalid metric name {full!r}")
        if full not in self._declared:
            self._declared.add(full)
            if help_:
                self._lines.append(f"# HELP {full} {help_}")
            self._lines.append(f"# TYPE {full} {mtype}")
        label_s = ""
        if labels:
            for k in labels:
                if not _LABEL_RE.match(k):
                    raise ValueError(f"invalid label name {k!r}")
            label_s = (
                "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels.items()) + "}"
            )
        self._lines.append(f"{full}{label_s} {fv}")

    def add_histogram(self, name: str, snap: Optional[Dict[str, Any]], *,
                      labels: Optional[Dict[str, Any]] = None,
                      help_: str = "") -> None:
        """Emit one Prometheus histogram from a ``utils.metrics.Histogram``
        snapshot: ``<name>_bucket{le=...}`` (cumulative, ending ``+Inf``),
        ``<name>_sum``, ``<name>_count``. Skipped entirely when ``snap`` is
        None/empty — an absent histogram must not emit a torn family."""
        if not snap or not snap.get("buckets"):
            return
        full = self.prefix + name
        if not _NAME_RE.match(full):
            raise ValueError(f"invalid metric name {full!r}")
        if full not in self._declared:
            self._declared.add(full)
            if help_:
                self._lines.append(f"# HELP {full} {help_}")
            self._lines.append(f"# TYPE {full} histogram")
        base = dict(labels or {})
        bounds = sorted(
            (k for k in snap["buckets"] if k != "+Inf"), key=float
        )
        for b in bounds:
            le = _fmt_value(float(b))
            self._emit_sample(f"{full}_bucket", {**base, "le": le},
                              snap["buckets"][b])
        self._emit_sample(f"{full}_bucket", {**base, "le": "+Inf"},
                          snap["buckets"]["+Inf"])
        self._emit_sample(f"{full}_sum", base, snap.get("sum", 0.0))
        self._emit_sample(f"{full}_count", base, snap.get("count", 0))

    def _emit_sample(self, full: str, labels: Dict[str, Any], value: Any) -> None:
        fv = _fmt_value(value)
        if fv is None:
            return
        label_s = ""
        if labels:
            for k in labels:
                if not _LABEL_RE.match(k):
                    raise ValueError(f"invalid label name {k!r}")
            label_s = (
                "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels.items()) + "}"
            )
        self._lines.append(f"{full}{label_s} {fv}")

    def render(self) -> str:
        return "\n".join(self._lines) + "\n"


def render_hbm(out: PromText) -> None:
    from galvatron_tpu.obs.stepstats import hbm_gauges

    for key, v in hbm_gauges().items():
        dev, _, kind = key.partition("_")
        out.add("hbm_bytes", v, labels={"device": dev, "kind": kind},
                help_="per-device HBM usage where the backend reports it")


def render_slo(out: PromText, slo) -> None:
    """Emit the SLO engine's per-rule gauges (obs/slo.py): burn rates,
    breach state and totals, one row per rule. No-op when no engine is
    armed — the families simply don't exist then."""
    if slo is None:
        return
    for row in slo.gauges():
        labels = {"rule": row["rule"]}
        out.add("slo_burn_rate_fast", row.get("burn_fast"), labels=labels,
                help_="error-budget burn rate over the fast window")
        out.add("slo_burn_rate_slow", row.get("burn_slow"), labels=labels,
                help_="error-budget burn rate over the slow window")
        out.add("slo_breached", 1 if row.get("breached") else 0, labels=labels,
                help_="1 while the rule's fast AND slow burn rates exceed "
                "their thresholds")
        out.add("slo_breaches_total", row.get("breaches_total"), labels=labels,
                mtype="counter", help_="breach events raised by this rule")
        out.add("slo_value", row.get("value"), labels=labels,
                help_="rule-specific observed value (ratio or seconds)")


def server_metrics_text(service) -> str:
    """Exposition for ``server.GenerationService``: request counters, the
    legacy gate, and — with the continuous-batching engine — the full serving
    stats incl. TTFT quantiles."""
    out = PromText()
    out.add("server_uptime_seconds", time.time() - service.started_at,
            help_="seconds since the generation service started")
    for outcome, v in service.counters.snapshot().items():
        out.add("server_requests_total", v, labels={"outcome": outcome},
                mtype="counter", help_="API requests by outcome")
    if service.gate is not None:
        g = service.gate.snapshot()
        out.add("server_gate_in_use", g["in_use"])
        out.add("server_gate_capacity", g["capacity"])
        out.add("server_gate_rejected_total", g["rejected"], mtype="counter")
    out.add("server_ready", 1 if service.ready else 0,
            help_="accepting new work (0 while draining or engine dead)")
    out.add("server_draining", 1 if service.draining else 0)
    eng = service.engine
    if eng is not None:
        s = eng.stats()
        for name in ("steps", "prefill_chunks", "prefill_tokens",
                     "tokens_generated", "submitted", "admitted", "completed",
                     "failed", "expired", "expired_decode", "cancelled",
                     "cancelled_disconnect", "shed"):
            out.add(f"serving_{name}_total", s[name], mtype="counter")
        out.add("serving_rejected_queue_full_total", s["rejected_queue_full"],
                mtype="counter")
        out.add("serving_engine_restarts_total", s["engine_restarts"],
                mtype="counter",
                help_="in-process engine crash-supervision restarts "
                "(serving/resilience.py)")
        for name in ("queue_depth", "queue_capacity", "active_slots",
                     "num_slots", "occupancy", "tokens_per_s",
                     "tokens_per_s_last_step"):
            out.add(f"serving_{name}", s[name])
        out.add("serving_queue_saturated", s["queue_saturated"])
        out.add("serving_draining", s["draining"])
        for q, key in (("0.5", "ttft_p50_s"), ("0.95", "ttft_p95_s")):
            out.add("serving_ttft_seconds", s[key], labels={"quantile": q},
                    help_="time-to-first-token over the recent-request window")
        out.add("serving_max_seq_len_effective", s.get("max_seq_len_effective"),
                help_="cache capacity actually in force (a requested "
                "max_seq_len above the model's is clamped, with a warning)")
        # paged-KV backend families (serving/paged_kv.py) — absent entirely
        # under the slot backend, so a scraper keys on family presence
        if "kv_blocks_total" in s:
            out.add("kv_block_size", s["kv_block_size"],
                    help_="tokens per KV block (--kv_block_size)")
            out.add("kv_blocks_total", s["kv_blocks_total"],
                    help_="device KV block pool size, incl. the reserved "
                    "null block")
            out.add("kv_blocks_free", s["kv_blocks_free"])
            out.add("kv_blocks_cached", s["kv_blocks_cached"],
                    help_="refcount-0 prefix blocks held in the LRU "
                    "(reclaimable without losing correctness)")
            out.add("kv_blocks_active", s["kv_blocks_active"],
                    help_="blocks referenced by at least one live request")
            for name in ("hits", "misses", "evictions"):
                out.add(f"prefix_cache_{name}_total",
                        s.get(f"prefix_cache_{name}"), mtype="counter",
                        help_="prefix-cache block matches at admission "
                        "(cumulative across engine resets)"
                        if name == "hits" else "")
            out.add("kv_cow_copies_total", s.get("cow_copies"),
                    mtype="counter",
                    help_="copy-on-write block copies (shared block written)")
            for rid, held in sorted((s.get("blocks_held") or {}).items()):
                out.add("kv_blocks_held", held, labels={"rid": rid},
                        help_="blocks reserved by each live request "
                        "(rid label; rows exist only while the request "
                        "holds a slot)")
        # cumulative histograms beside the quantile gauges: quantiles are a
        # single-process readout; buckets aggregate across replicas (the
        # fleet router sums them — fleet_metrics_text)
        out.add_histogram("serving_ttft_hist_seconds", s.get("ttft_hist"),
                          help_="time-to-first-token (cumulative buckets)")
        out.add_histogram("serving_latency_hist_seconds", s.get("latency_hist"),
                          help_="request e2e latency, submit to completion "
                          "(cumulative buckets)")
        # decode-step observability: the per-ITERATION hot path, plus the
        # speculative-decoding draft economy and the quant/spec numerics
        # config the replica is actually serving under (config in labels —
        # a fleet scrape diffing this row across replicas is the cheap
        # cross-replica consistency check)
        out.add_histogram("serving_decode_step_hist_seconds",
                          s.get("decode_step_hist"),
                          help_="per-iteration decode step latency "
                          "(cumulative buckets, finer than the "
                          "request-level histograms)")
        for name in ("draft_proposed", "draft_accepted", "spec_steps",
                     "spec_fallbacks"):
            out.add(f"serving_{name}_total", s.get(name), mtype="counter",
                    help_="speculative-decoding draft tokens proposed by "
                    "the prompt-lookup drafter"
                    if name == "draft_proposed" else "")
        out.add("serving_accepted_tokens_per_step",
                s.get("accepted_tokens_per_step"),
                help_="tokens emitted per decode iteration (batched over "
                "slots, so ~active-slot width without spec); rising above "
                "that width means speculative acceptance is paying")
        out.add("serving_draft_acceptance_rate",
                s.get("draft_acceptance_rate"),
                help_="draft_accepted / draft_proposed (cumulative)")
        if "serve_quant" in s:
            out.add("serving_numerics_info", 1, labels={
                "serve_quant": s.get("serve_quant"),
                "spec_decode_k": s.get("spec_decode_k"),
                "spec_drafter": s.get("spec_drafter") or "off",
            }, help_="serving numerics/speed config (constant 1; config "
                     "in labels)")
        qp = s.get("quant_parity") or {}
        out.add("serving_quant_max_abs_logit_drift",
                qp.get("max_abs_logit_drift"),
                help_="int8-vs-fp logit drift measured on the load-time "
                "parity probe (gate bound in "
                "serving_quant_drift_bound)")
        out.add("serving_quant_drift_bound", qp.get("drift_bound"))
        out.add("serving_quant_greedy_agree_frac", qp.get("greedy_agree_frac"),
                help_="fraction of parity-probe positions whose int8 "
                "greedy token matches fp")
        # runtime lock validator counters (analysis/locks.py) — present only
        # when GALVATRON_LOCK_CHECK armed the instrumented primitives; lock
        # name in a label so one family covers the whole control plane
        for lname, row in sorted((s.get("lock_stats") or {}).items()):
            labels = {"lock": lname}
            out.add("lock_hold_ms", row.get("hold_ms"), labels=labels,
                    mtype="counter",
                    help_="cumulative milliseconds each named lock was held "
                    "(GALVATRON_LOCK_CHECK=1 only)")
            out.add("lock_contended_total", row.get("contended_total"),
                    labels=labels, mtype="counter",
                    help_="acquisitions that had to wait (uncontended "
                    "fast path failed)")
            out.add("lock_acquired_total", row.get("acquired_total"),
                    labels=labels, mtype="counter")
    render_slo(out, getattr(service, "slo", None))
    c = service.cfg
    out.add("model_info", 1, labels={
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "num_layers": c.num_layers, "num_heads": c.num_heads,
        "max_seq_len": c.max_seq_len,
    }, help_="model shape (constant 1; shape in labels)")
    render_hbm(out)
    return out.render()


def fleet_metrics_text(router) -> str:
    """Exposition for ``serving.fleet.FleetRouter``: fleet-level request
    counters, the shared admission gate, and one state/restart row per
    replica — the scrape a load balancer's operator actually needs (is the
    fleet degraded? is one replica crash-looping?)."""
    out = PromText()
    out.add("fleet_uptime_seconds", time.time() - router.started_at)
    out.add("fleet_replicas", len(router.replicas),
            help_="configured replica count")
    out.add("fleet_ready_replicas", router.ready_count(),
            help_="replicas currently dispatchable (READY + reachable)")
    out.add("fleet_ready", 1 if router.ready else 0)
    out.add("fleet_draining", 1 if router.draining else 0)
    for name, v in router.counters.snapshot().items():
        if name == "replica_restarts":
            # exposed ONLY as the per-replica labeled family below — a
            # second unlabeled sample under the same name would split the
            # family and double-count on sum()
            continue
        out.add(f"fleet_{name}_total", v, mtype="counter",
                help_="fleet router request accounting" if name == "dispatched"
                else "")
    g = router.gate.snapshot()
    out.add("fleet_gate_in_use", g["in_use"])
    out.add("fleet_gate_capacity", g["capacity"])
    for r in router.replicas:
        labels = {"idx": r.idx}
        out.add("fleet_replica_state_info", 1,
                labels={**labels, "state": r.state},
                help_="per-replica lifecycle state (in labels)")
        out.add("fleet_replica_restarts_total", r.restarts_total,
                labels=labels, mtype="counter")
        out.add("fleet_replica_outstanding", r.outstanding, labels=labels,
                help_="router-side in-flight dispatches on this replica")
        s = (r.last_health.get("serving") or {})
        out.add("fleet_replica_queue_depth", s.get("queue_depth"),
                labels=labels)
        out.add("fleet_replica_active_slots", s.get("active_slots"),
                labels=labels)
        out.add("fleet_replica_completed_total", s.get("completed"),
                labels=labels, mtype="counter",
                help_="completions of the replica's CURRENT incarnation")
    # --- aggregation: the router is the fleet's single scrape target -------
    # per-replica labeled serving families (label scheme: replica="<idx>")
    # plus fleet-level sums; TTFT/latency aggregate as HISTOGRAMS because
    # bucket counts sum across replicas — quantiles don't.
    replica_stats = [
        (r, (r.last_health.get("serving") or {})) for r in router.replicas
    ]
    for name in ("tokens_generated", "completed", "failed", "expired",
                 "prefix_cache_hits", "prefix_cache_misses",
                 "prefix_cache_evictions", "draft_proposed",
                 "draft_accepted", "spec_steps", "spec_fallbacks"):
        total = 0
        seen = False
        for r, s in replica_stats:
            v = s.get(name)
            if v is None:
                continue
            seen = True
            total += v
            out.add(f"fleet_serving_{name}_total", v,
                    labels={"replica": r.idx}, mtype="counter",
                    help_="per-replica engine counter (replica label); the "
                    "unlabeled-sum lives in fleet_serving_*_sum_total"
                    if name == "tokens_generated" else "")
        if seen:
            out.add(f"fleet_serving_{name}_sum_total", total, mtype="counter",
                    help_="sum over currently-reachable replicas")
    # per-replica-only rate gauges: a cross-replica SUM of a rate is
    # meaningless, so these get no `_sum` twin (the summable raw counters
    # draft_proposed/draft_accepted are in the counter rollup above)
    for name in ("accepted_tokens_per_step", "draft_acceptance_rate"):
        for r, s in replica_stats:
            out.add(f"fleet_serving_{name}", s.get(name),
                    labels={"replica": r.idx})
    for name in ("queue_depth", "active_slots", "tokens_per_s",
                 "kv_blocks_total", "kv_blocks_free"):
        total = 0.0
        seen = False
        for r, s in replica_stats:
            v = s.get(name)
            if v is None:
                continue
            seen = True
            total += v
            out.add(f"fleet_serving_{name}", v, labels={"replica": r.idx})
        if seen:
            out.add(f"fleet_serving_{name}_sum", total)
    from galvatron_tpu.utils.metrics import Histogram

    for hist_key, fam in (("ttft_hist", "fleet_ttft_hist_seconds"),
                          ("latency_hist", "fleet_latency_hist_seconds"),
                          ("decode_step_hist",
                           "fleet_decode_step_hist_seconds")):
        snaps = [s[hist_key] for _, s in replica_stats if s.get(hist_key)]
        for r, s in replica_stats:
            if s.get(hist_key):
                out.add_histogram(fam, s[hist_key], labels={"replica": r.idx})
        if snaps:
            out.add_histogram(
                f"{fam}_fleet",
                Histogram.merge_snapshots(snaps),
                help_="fleet-level distribution: per-replica bucket counts "
                "summed (the reason histograms exist beside the quantile "
                "gauges)")
    # lock validator rollup: per-(replica, lock) rows plus a fleet-level sum
    # per lock name — a lock hot on ONE replica (skewed traffic) and a lock
    # hot on ALL of them (systemic contention) read differently
    lock_rollup: Dict[str, List[float]] = {}
    for r, s in replica_stats:
        for lname, row in sorted((s.get("lock_stats") or {}).items()):
            labels = {"replica": r.idx, "lock": lname}
            out.add("fleet_lock_hold_ms", row.get("hold_ms"), labels=labels,
                    mtype="counter",
                    help_="cumulative lock hold milliseconds per replica "
                    "(GALVATRON_LOCK_CHECK=1 replicas only)")
            out.add("fleet_lock_contended_total", row.get("contended_total"),
                    labels=labels, mtype="counter")
            agg = lock_rollup.setdefault(lname, [0.0, 0.0])
            agg[0] += float(row.get("hold_ms") or 0.0)
            agg[1] += float(row.get("contended_total") or 0.0)
    for lname, (hold, cont) in sorted(lock_rollup.items()):
        out.add("fleet_lock_hold_ms_sum", hold, labels={"lock": lname},
                mtype="counter",
                help_="sum over currently-reachable replicas")
        out.add("fleet_lock_contended_sum_total", cont,
                labels={"lock": lname}, mtype="counter")
    render_slo(out, getattr(router, "slo", None))
    return out.render()


class TrainStats:
    """Mutable per-run gauge set the trainer updates each iteration and the
    sidecar renders on scrape. Plain attribute writes under the GIL — the
    trainer loop must not pay a lock for observability."""

    def __init__(self):
        self.started_at = time.time()
        self.iterations = 0
        self.last_loss: Optional[float] = None
        self.last_iter_ms: Optional[float] = None
        self.tokens_per_s: Optional[float] = None
        self.tflops_per_device: Optional[float] = None
        self.mfu: Optional[float] = None
        self.hfu: Optional[float] = None
        self.anomaly_skips = 0
        self.checkpoints_saved = 0
        self.packing_efficiency: Optional[float] = None
        # comm/compute overlap accounting (obs/stepstats.per_iter; DESIGN.md
        # "Overlap"): per-step non-compute exposure — the numbers the overlap
        # work (collective-matmul, grad_overlap, --xla_overlap) must move
        self.comm_wait_ms: Optional[float] = None
        self.bubble_fraction: Optional[float] = None
        # AOT compile subsystem (galvatron_tpu/aot): startup warmup accounting
        self.compile_cache_hits: Optional[int] = None
        self.compile_cache_misses: Optional[int] = None
        self.startup_compile_ms: Optional[float] = None
        # predicted-vs-observed step time (obs/slo.py step_time_drift rule):
        # the quantitative signal ROADMAP item 2's online re-planner triggers
        # on — positive means the plan is running slower than the cost model
        # promised
        self.predicted_iter_ms: Optional[float] = None
        self.step_time_drift: Optional[float] = None

    def render(self) -> str:
        out = PromText()
        out.add("train_uptime_seconds", time.time() - self.started_at)
        out.add("train_iterations_total", self.iterations, mtype="counter",
                help_="optimizer iterations completed this run")
        out.add("train_anomaly_skips_total", self.anomaly_skips, mtype="counter")
        out.add("train_checkpoints_saved_total", self.checkpoints_saved,
                mtype="counter")
        loss = self.last_loss
        out.add("train_last_loss", loss if loss is None or math.isfinite(loss)
                else float("nan"))
        out.add("train_last_iter_ms", self.last_iter_ms)
        out.add("train_tokens_per_s", self.tokens_per_s)
        out.add("train_tflops_per_device", self.tflops_per_device,
                help_="achieved model TFLOP/s per device")
        out.add("train_mfu", self.mfu, help_="model FLOPs utilization (PaLM convention)")
        out.add("train_hfu", self.hfu, help_="hardware FLOPs utilization (incl. remat)")
        out.add("train_packing_efficiency", self.packing_efficiency,
                help_="non-pad fraction of packed input rows (None-skipped "
                "when sequence packing is off)")
        out.add("train_comm_wait_ms", self.comm_wait_ms,
                help_="per-step time above the hardware-FLOPs ideal — "
                "collective exposure + launch gaps (read as a paired "
                "overlap-on/off delta, not an absolute)")
        out.add("train_bubble_fraction", self.bubble_fraction,
                help_="fraction of the step spent off the MXUs (1 - "
                "ideal_ms/iter_ms); decreases when overlap is on")
        out.add("train_compile_cache_hits", self.compile_cache_hits,
                mtype="counter",
                help_="startup AOT warmup programs served warm from the "
                "compile-artifact cache (galvatron_tpu/aot)")
        out.add("train_compile_cache_misses", self.compile_cache_misses,
                mtype="counter",
                help_="startup AOT warmup programs that paid a real XLA compile")
        out.add("train_startup_compile_ms", self.startup_compile_ms,
                help_="wall ms the startup AOT warmup spent compiling "
                "(deserialization only on a warm start)")
        out.add("train_predicted_iter_ms", self.predicted_iter_ms,
                help_="cost model's predicted step time for the active plan")
        out.add("train_step_time_drift", self.step_time_drift,
                help_="(iter_ms - predicted_ms) / predicted_ms — the re-plan "
                "trigger signal (ROADMAP item 2)")
        render_hbm(out)
        return out.render()


class ElasticStats:
    """Supervisor-side gauge set for elastic training (`core/elastic.py`):
    the ``--obs_port`` sidecar of a supervised run is owned by the
    SUPERVISOR (the child gets its port stripped — two listeners on one
    port), and what an operator needs from it is the restart story: is this
    a re-planning topology resume or a crash loop? Rendered on ``/metrics``
    and, as plain JSON, on ``/healthz`` (:meth:`health`)."""

    def __init__(self):
        self.started_at = time.time()
        self.restarts_total = 0
        self.replans_total = 0
        self.last_exit_mode: Optional[str] = None
        self.last_exit_code: Optional[int] = None
        self.watchdog_armed = False  # current child launched with --step_timeout_s
        self.child_alive = False
        self.current_plan_hash: Optional[str] = None
        self.world_size: Optional[int] = None
        self.last_step: Optional[int] = None
        # preemption-aware recovery story (core/peer_store.py tier): how
        # many times the run restored, from where, and how long it was down
        self.recoveries_total = 0
        self.last_recovery_source: Optional[str] = None  # "peer" | "disk"
        self.last_recovery_ms: Optional[float] = None
        # fleet-wide aggregation: the supervisor owns the ONLY sidecar port
        # of a supervised run, so the child's train gauges must surface here
        # — the supervisor injects --metrics_path into the child and tails
        # the newest train_iter record at scrape time (no IPC, no second
        # port; the JSONL file is already the cross-restart contract)
        self.child_metrics_path: Optional[str] = None

    def child_train_gauges(self) -> Dict[str, Any]:
        """Newest ``train_iter`` record from the child's metrics JSONL —
        read on scrape (tail ~64KB), tolerant of a torn tail and of future
        schema fields. Empty dict before the child's first iteration."""
        path = self.child_metrics_path
        if not path:
            return {}
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - 65536))
                lines = f.read().split(b"\n")
        except OSError:
            return {}
        for raw in reversed(lines):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue  # torn tail mid-write: walk back one record
            if rec.get("event") == "train_iter":
                return rec
        return {}

    def health(self) -> Dict[str, Any]:
        """The ``/healthz`` JSON body — the same supervisor state, scrapeless."""
        return {
            "status": "ok",
            "restarts_total": self.restarts_total,
            "replans_total": self.replans_total,
            "last_exit_mode": self.last_exit_mode,
            "last_exit_code": self.last_exit_code,
            "watchdog_armed": self.watchdog_armed,
            "child_alive": self.child_alive,
            "current_plan_hash": self.current_plan_hash,
            "world_size": self.world_size,
            "last_step": self.last_step,
            "recoveries_total": self.recoveries_total,
            "last_recovery_source": self.last_recovery_source,
            "last_recovery_ms": self.last_recovery_ms,
        }

    def render(self) -> str:
        out = PromText()
        out.add("elastic_uptime_seconds", time.time() - self.started_at)
        out.add("elastic_restarts_total", self.restarts_total, mtype="counter",
                help_="child restarts issued by the elastic supervisor")
        out.add("elastic_replans_total", self.replans_total, mtype="counter",
                help_="topology-change re-plans (GTA017 resumes)")
        out.add("elastic_child_alive", self.child_alive)
        out.add("elastic_watchdog_armed", self.watchdog_armed,
                help_="current child runs under a --step_timeout_s hang watchdog")
        if self.last_exit_mode is not None:
            out.add("elastic_last_exit_mode_info", 1,
                    labels={"mode": self.last_exit_mode,
                            "code": self.last_exit_code},
                    help_="most recent child exit classification (mode in labels)")
        if self.current_plan_hash is not None:
            out.add("elastic_current_plan_info", 1,
                    labels={"plan_hash": self.current_plan_hash},
                    help_="plan hash the run is currently training under")
        out.add("elastic_world_size", self.world_size)
        out.add("elastic_last_step", self.last_step,
                help_="newest committed checkpoint step")
        # child train gauges, aggregated through the JSONL metrics file so a
        # pod dashboard needs ONE port for the whole supervised run
        rec = self.child_train_gauges()
        out.add("elastic_child_step", rec.get("step"),
                help_="child trainer's newest logged iteration")
        out.add("elastic_child_loss", rec.get("loss"))
        out.add("elastic_child_iter_ms", rec.get("iter_ms"))
        out.add("elastic_child_mfu", rec.get("mfu"),
                help_="child trainer's model FLOPs utilization")
        out.add("elastic_child_bubble_fraction", rec.get("bubble_fraction"))
        out.add("elastic_child_tokens_per_s", rec.get("tokens_per_s"))
        out.add("elastic_child_step_time_drift", rec.get("step_time_drift"),
                help_="child's predicted-vs-observed step-time drift (the "
                "re-plan trigger, surfaced at the supervisor)")
        # recovery story: restores observed across child restarts (source
        # "peer" = in-memory replica beat disk; MTTR = child death → child
        # `recovery` event, the operator's actual downtime)
        out.add("elastic_recoveries_total", self.recoveries_total,
                mtype="counter",
                help_="child restores observed (peer replica or disk)")
        if self.last_recovery_source is not None:
            out.add("elastic_last_recovery_info", 1,
                    labels={"source": self.last_recovery_source},
                    help_="where the most recent restore came from")
        out.add("elastic_last_recovery_ms", self.last_recovery_ms,
                help_="most recent MTTR: previous child exit to this "
                "child's recovery event, wall ms")
        # transient-I/O retry telemetry (core/retry.py): a rising retry
        # rate is storage flakiness BEFORE it becomes an outage
        from galvatron_tpu.core.retry import RETRY_COUNTERS

        out.add("galvatron_io_retries_total",
                RETRY_COUNTERS.get("io_retry"), mtype="counter",
                help_="transient-I/O attempts that were retried")
        out.add("galvatron_io_retry_give_ups_total",
                RETRY_COUNTERS.get("io_give_up"), mtype="counter",
                help_="retry-protected calls that exhausted their budget")
        return out.render()


class ObsServer:
    """Sidecar HTTP listener for headless runs (``--obs_port``): serves
    ``GET /metrics`` (Prometheus text from ``metrics_fn``) and ``GET
    /healthz`` on its own daemon thread, so a training job with no serving
    stack is still scrapeable. ``health_fn`` (optional) supplies the
    ``/healthz`` JSON body — the elastic supervisor publishes its restart
    state there. ``port=0`` binds an ephemeral port (tests)."""

    def __init__(self, metrics_fn: Callable[[], str], port: int = 0,
                 host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        # loopback by default, matching run_server: an unauthenticated
        # telemetry endpoint must not silently bind all interfaces
        self.metrics_fn = metrics_fn
        self.health_fn = health_fn
        obs = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/")
                try:
                    if path == "/metrics":
                        body = obs.metrics_fn().encode()
                        ctype = CONTENT_TYPE
                    elif path == "/healthz":
                        doc = obs.health_fn() if obs.health_fn else {"status": "ok"}
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                    else:
                        body = b'{"error": "use /metrics or /healthz"}'
                        self._send(404, body, "application/json")
                        return
                    self._send(200, body, ctype)
                except Exception as e:  # noqa: BLE001 — scrape must not kill the run
                    self._send(500, f"# render error: {e}\n".encode(), "text/plain")

            def _send(self, code, body, ctype):
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True

            def log_message(self, *a):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-sidecar", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)
