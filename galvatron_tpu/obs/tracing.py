"""Span tracer: nestable named host-side spans with Perfetto export.

The reference's observability is print-based (SURVEY §5); production
trainers (MegaScale §5, NSDI '24) treat a per-step span timeline as a
first-class subsystem. This module is the host half of that layer:

- ``tracer.span("fwd_bwd", step=i)`` — a nestable context manager recording
  wall-clock spans into a bounded in-memory ring (thread-aware: concurrent
  threads get their own nesting stacks and their own timeline tracks).
- ``Span.sync(value)`` — an explicit ``jax.block_until_ready`` measurement
  boundary, so a span can close on *device completion* rather than dispatch
  return. Tracing OFF is the hot-path default and adds **zero** host syncs:
  ``tracer.span`` returns a no-op singleton without reading the clock.
- ``chrome_trace(spans)`` / ``export_chrome_trace(path)`` — Chrome
  trace-event JSON (the format Perfetto and chrome://tracing load).
- synthetic schedule spans (``emit_tick_spans``) — pipeline schedules run
  inside ONE jitted clocked scan, so no host probe can observe per-tick
  activity; instead the schedule's exact clock model (the same index
  arithmetic the scan executes — ``gpipe_schedule_ticks`` /
  ``pipedream_schedule_ticks``) is rendered onto the measured step window,
  one track per stage. Gaps on a stage track are the schedule's bubbles.
  These spans are labeled ``synthetic: true``: they are the schedule's
  lockstep model scaled to the measured step, not a device-side measurement
  (the XLA op timeline for that lives in ``--trace_dir``/``--profile_steps``).

The module-level ``tracer`` singleton is what the trainer, checkpoint layer,
search engine, and serving engine all record into — enable it once
(``tracer.enable()``) and every subsystem's spans land on one timeline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

# process-wide pid for the trace; Chrome trace groups tracks by (pid, tid)
_PID = os.getpid()


class _NullSpan:
    """Singleton no-op span: returned when tracing is disabled so the hot
    path costs one attribute read and no clock access, no allocation, and —
    critically — ``sync`` does NOT block (tracing off ⇒ zero host syncs)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def sync(self, value=None):
        return value

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class Span:
    """One live span; records itself into the tracer ring on ``__exit__``."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_tid", "_tname", "_synced")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0.0
        self._synced = False
        t = threading.current_thread()
        self._tid = t.ident or 0
        self._tname = t.name

    def __enter__(self):
        self._tracer._stack_for_thread().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def sync(self, value=None):
        """Block until ``value`` (a jax array/tree) is device-complete, so
        the span measures realized compute, not dispatch. Returns ``value``."""
        if value is not None:
            jax.block_until_ready(value)
        self._synced = True
        return value

    def set(self, **attrs):
        self.args.update(attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        stack = self._tracer._stack_for_thread()
        if stack:
            stack.pop()
        args = self.args
        if self._synced:
            args = {**args, "synced": True}
        if exc_type is not None:
            args = {**args, "error": exc_type.__name__}
        self._tracer._record(
            {
                "name": self.name,
                "ph": "X",
                "ts": self._tracer.pc_to_us(self._t0),
                "dur": (t1 - self._t0) * 1e6,
                "tid": self._tid,
                "tname": self._tname,
                "depth": len(stack),
                "args": args,
            }
        )
        return False


class Tracer:
    """Thread-aware span recorder over a bounded ring.

    ``enabled`` gates everything: disabled (the default), ``span``/``instant``
    return/do nothing without touching the clock. The ring is a
    ``deque(maxlen=capacity)`` — the flight recorder's "last N spans before
    the crash" is exactly its contents (obs/flight.py dumps it)."""

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._ring: deque = deque(maxlen=capacity)
        self._local = threading.local()
        self._epoch_pc = time.perf_counter()
        self._epoch_wall = time.time()

    # -- lifecycle ----------------------------------------------------------

    def enable(self, capacity: Optional[int] = None) -> "Tracer":
        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=max(16, capacity))
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def clear(self) -> None:
        self._ring.clear()

    # -- recording ----------------------------------------------------------

    def span(self, name: str, **attrs):
        """``with tracer.span("step", step=i) as sp: ...`` — no-op singleton
        when disabled (zero clock reads, zero syncs)."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, attrs)

    def instant(self, name: str, **attrs) -> None:
        """Point event (anomaly skips, fallbacks, emergency saves): shows as
        an instant marker on the timeline and in flight dumps."""
        if not self.enabled:
            return
        t = threading.current_thread()
        self._record(
            {
                "name": name,
                "ph": "i",
                "ts": self.pc_to_us(time.perf_counter()),
                "tid": t.ident or 0,
                "tname": t.name,
                "args": attrs,
            }
        )

    def _record(self, rec: Dict[str, Any]) -> None:
        # deque.append with maxlen is atomic in CPython — no lock on the hot
        # path; snapshot() copies defensively for readers
        self._ring.append(rec)

    def _stack_for_thread(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def pc_to_us(self, pc: float) -> float:
        return (pc - self._epoch_pc) * 1e6

    # -- readout ------------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    @property
    def epoch_wall(self) -> float:
        """Wall-clock time of the tracer's perf_counter epoch (ts=0)."""
        return self._epoch_wall

    def export_chrome_trace(self, path: str) -> str:
        doc = chrome_trace(self.snapshot())
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


#: the process-wide tracer every subsystem records into
tracer = Tracer()


# ---------------------------------------------------------------------------
# Chrome trace-event / Perfetto export
# ---------------------------------------------------------------------------


def chrome_trace(
    spans: Sequence[Dict[str, Any]],
    pid: Optional[int] = None,
    ts_offset_us: float = 0.0,
    process_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Render recorded spans as a Chrome trace-event JSON object (Perfetto /
    chrome://tracing load this directly). Span records are the tracer's ring
    schema; thread/track names become thread_name metadata events.

    ``pid``/``ts_offset_us``/``process_name`` exist for the multi-process
    merge (obs/correlate.py): each source dump renders under its own pid
    (its own track group) with its timestamps shifted onto the shared
    reference clock. Defaults reproduce the single-process export exactly."""
    use_pid = _PID if pid is None else int(pid)
    events: List[Dict[str, Any]] = []
    named: Dict[Tuple[int, int], str] = {}
    for rec in spans:
        tid = int(rec.get("tid", 0))
        ev: Dict[str, Any] = {
            "name": rec["name"],
            "ph": rec.get("ph", "X"),
            "pid": use_pid,
            "tid": tid,
            "ts": round(float(rec["ts"]) + ts_offset_us, 3),
            "args": dict(rec.get("args", {})),
        }
        if ev["ph"] == "X":
            ev["dur"] = round(float(rec.get("dur", 0.0)), 3)
        elif ev["ph"] == "i":
            ev["s"] = "t"
        events.append(ev)
        tname = rec.get("tname")
        if tname and named.get((use_pid, tid)) != tname:
            named[(use_pid, tid)] = tname
    for (epid, tid), tname in named.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": epid,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    if process_name:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": use_pid,
                "tid": 0,
                "args": {"name": process_name},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Synthetic pipeline-schedule spans
# ---------------------------------------------------------------------------

# synthetic stage tracks live at tids far from real thread idents
_STAGE_TID_BASE = 1_000_000
#: relative tick weights (the cost model's bwd = 2x fwd convention,
#: reference galvatron/core/cost_model.py:190-191)
_TICK_WEIGHTS = {"fwd": 1.0, "bwd": 2.0}


def emit_tick_spans(
    trc: Tracer,
    ticks: Sequence[Dict[str, int]],
    total_ticks: int,
    t0_us: float,
    dur_us: float,
    step: Optional[int] = None,
) -> int:
    """Render a schedule's tick grid onto the measured step window.

    ``ticks``: ``{"stage", "tick", "kind" ("fwd"|"bwd"), "mb"}`` records from
    ``gpipe_schedule_ticks``/``pipedream_schedule_ticks``. Each stage gets
    its own synthetic track (``pp stage S``); within a tick that carries both
    a forward and a backward (1F1B steady state), the tick is split by the
    fwd:bwd = 1:2 cost convention. Ticks with no work emit nothing — the
    gaps on a stage track ARE the schedule's bubbles. Returns span count."""
    if not trc.enabled or not ticks or total_ticks <= 0 or dur_us <= 0:
        return 0
    tick_us = dur_us / total_ticks
    by_cell: Dict[Tuple[int, int], List[Dict[str, int]]] = {}
    for t in ticks:
        by_cell.setdefault((t["stage"], t["tick"]), []).append(t)
    n = 0
    for (stage, tick), cell in sorted(by_cell.items()):
        cell_t0 = t0_us + tick * tick_us
        wsum = sum(_TICK_WEIGHTS.get(c["kind"], 1.0) for c in cell)
        off = 0.0
        # fwd renders before bwd within a shared tick (the 1F1B last stage
        # forwards a micro-batch, then backwards it, in one tick)
        for c in sorted(cell, key=lambda c: 0 if c["kind"] == "fwd" else 1):
            frac = _TICK_WEIGHTS.get(c["kind"], 1.0) / wsum
            args: Dict[str, Any] = {
                "mb": c["mb"], "tick": tick, "synthetic": True,
                "model": "lockstep clocked schedule",
            }
            if step is not None:
                args["step"] = step
            trc._record(
                {
                    "name": f"stage{stage} {c['kind']} mb{c['mb']}",
                    "ph": "X",
                    "ts": cell_t0 + off * tick_us,
                    "dur": frac * tick_us,
                    "tid": _STAGE_TID_BASE + stage,
                    "tname": f"pp stage {stage}",
                    "args": args,
                }
            )
            off += frac
            n += 1
    return n
