"""Crash flight recorder + programmatic jax.profiler capture windows.

Flight recorder (MegaScale's event recorder, NSDI '24 §5): the tracer's
bounded ring IS the recorder — the last N spans/instants before a crash.
``dump_flight`` serializes it to ``flight_<ts>.json`` and is called from the
trainer's PR 1 crash ``finally`` path, so every exceptional exit leaves the
seconds-before-the-crash timeline on disk next to the emergency checkpoint.
``galvatron_tpu.cli trace-export flight_*.json`` turns a dump back into a
Perfetto-loadable trace.

Profiler capture: ``--profile_steps A:B`` (trainer) and ``POST
/profile?steps=N`` (server) open a bounded ``jax.profiler`` window — the
full XLA op/kernel timeline for exactly the steps asked for, instead of the
whole-run ``--trace_dir`` firehose. Backends without xprof support degrade
to a logged warning: profiling is an observation, never a crash source.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Callable, Dict, Optional, Tuple

FLIGHT_SCHEMA = "galvatron-flight-v1"


def dump_flight(
    out_dir: str, trc, reason: str, extra: Optional[Dict[str, Any]] = None
) -> Optional[str]:
    """Write the tracer ring (+ context) to ``<out_dir>/flight_<ts>.json``.
    Returns the path, or None when there is nothing to record (tracing was
    never enabled and the ring is empty). Never raises — callers sit in
    crash ``finally`` blocks where a dump failure must not mask the crash."""
    try:
        spans = trc.snapshot()
        if not spans and not trc.enabled:
            return None
        os.makedirs(out_dir, exist_ok=True)
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(out_dir, f"flight_{ts}_{os.getpid()}.json")
        doc: Dict[str, Any] = {
            "schema": FLIGHT_SCHEMA,
            "wall_time": time.time(),
            "epoch_wall": trc.epoch_wall,  # wall clock at span ts=0
            "pid": os.getpid(),  # merge-export keys each dump's track group
            "reason": reason,
            "spans": spans,
        }
        if extra:
            doc["extra"] = extra
        try:
            from galvatron_tpu.obs.stepstats import hbm_gauges

            doc["hbm_bytes"] = hbm_gauges()
        except Exception:
            pass
        try:
            # under GALVATRON_LOCK_CHECK=1 the dump answers "which thread
            # holds what" directly — the first question of any hang forensic
            from galvatron_tpu.analysis.locks import held_snapshot, lock_check_armed

            if lock_check_armed():
                doc["held_locks"] = held_snapshot()
        except Exception:
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception as e:  # noqa: BLE001 — crash-path best effort
        print(f"flight-recorder dump failed: {e!r}")
        return None


def read_flight(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(f"{path}: not a {FLIGHT_SCHEMA} dump")
    return doc


def parse_profile_steps(spec: str) -> Tuple[int, int]:
    """``"A:B"`` → (A, B): capture iterations A..B-1 (half-open, like range).
    Validated loudly — a silently-ignored malformed window would look like a
    backend limitation instead of a typo."""
    m = re.fullmatch(r"(\d+):(\d+)", spec.strip())
    if not m:
        raise ValueError(
            f"--profile_steps expects START:STOP (e.g. 3:6), got {spec!r}"
        )
    a, b = int(m.group(1)), int(m.group(2))
    if b <= a:
        raise ValueError(f"--profile_steps {spec!r}: STOP must be > START")
    return a, b


class ProfilerWindow:
    """Step-bounded jax.profiler capture: ``maybe_start(it)`` /
    ``maybe_stop(it)`` around the trainer iteration. A backend without xprof
    (start_trace raising) disables the window with a warning and the run
    continues untraced."""

    def __init__(self, trace_dir: str, start_step: int, stop_step: int):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.stop_step = stop_step
        self.active = False
        self.failed = False
        self.done = False

    def maybe_start(self, it: int) -> None:
        # >= not ==: a resumed run whose batch offset already passed START
        # must still capture (from where it is) rather than silently skip
        if self.failed or self.active or self.done or it < self.start_step:
            return
        if it >= self.stop_step:
            self.done = True  # resumed entirely past the window: nothing to do
            return
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.active = True
        except Exception as e:  # noqa: BLE001 — degrade, don't crash training
            self.failed = True
            print(f"--profile_steps: backend lacks profiler support ({e!r}); "
                  "continuing without capture")

    def maybe_stop(self, it: int, verbose: bool = True) -> None:
        if not self.active or it + 1 < self.stop_step:
            return
        self.close(verbose=verbose)

    def close(self, verbose: bool = True) -> None:
        """Idempotent stop — also called from the trainer ``finally`` so a
        crash inside the window cannot wedge process-wide profiler state."""
        if not self.active:
            return
        self.active = False
        self.done = True
        try:
            import jax

            jax.profiler.stop_trace()
            if verbose:
                print(f"profiler window [{self.start_step}:{self.stop_step}) "
                      f"→ {self.trace_dir}")
        except Exception as e:  # noqa: BLE001
            print(f"failed to close profiler window: {e!r}")


def capture_profile(
    trace_dir: str, n_steps: int, counter_fn: Callable[[], int],
    timeout_s: float = 30.0, poll_s: float = 0.02,
) -> Dict[str, Any]:
    """On-demand capture (server ``POST /profile``): start a jax.profiler
    trace, wait until ``counter_fn`` advances by ``n_steps`` (engine decode
    iterations) or ``timeout_s`` elapses, stop, report what happened.
    Raises RuntimeError when the backend cannot start a trace at all."""
    import jax

    start_count = counter_fn()
    try:
        jax.profiler.start_trace(trace_dir)
    except Exception as e:
        raise RuntimeError(f"profiler unavailable on this backend: {e!r}") from e
    deadline = time.time() + timeout_s
    try:
        while counter_fn() - start_count < n_steps and time.time() < deadline:
            time.sleep(poll_s)
    finally:
        captured = counter_fn() - start_count
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — report, the capture dir may still be usable
            return {"trace_dir": trace_dir, "steps_captured": captured,
                    "requested": n_steps, "stop_error": repr(e)}
    return {
        "trace_dir": trace_dir,
        "steps_captured": captured,
        "requested": n_steps,
        "timed_out": captured < n_steps,
    }
