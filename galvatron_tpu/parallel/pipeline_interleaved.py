"""Interleaved (virtual-pipeline-stage) schedule over the pp ring.

TPU-native rendering of the interleaved schedule the reference carries only
in its vendored Megatron (core/pipeline_parallel/schedules.py:367,
``--num-layers-per-virtual-pipeline-stage``) and never wires into Galvatron's
own engine (SURVEY §2.3 'PP' row). Here it is first-class: the model is cut
into ``vpp * pp`` *virtual stages*; device ``s`` holds virtual stages
``{s, s+pp, ..., s+(vpp-1)·pp}``, so each micro-batch travels the device ring
``vpp`` times. Ticks are one virtual stage long (1/vpp of a physical stage),
shrinking the pipeline-fill bubble from ``(pp-1)·T/pp`` to ``(pp-1)·T/(pp·vpp)``
— the same bubble/vpp factor as Megatron's interleaved 1F1B.

Schedule (all static arithmetic, one ``lax.scan``): micro-batches flow in
groups of ``pp`` (hence ``chunks % pp == 0``, the reference's own interleaved
constraint). At tick ``t`` device ``s`` computes virtual chunk ``j`` of
micro-batch ``m`` where, with ``n = t - s``::

    r = n mod pp;  q = n div pp;  j = q mod vpp;  g = q div vpp;  m = g·pp + r

This is a bijection (r, j, g) ↔ n, so every device is busy every tick of
``[s, s + vpp·chunks)`` — the only idle ticks are the ``pp-1``-tick ramp.
Sends ride one ring ``ppermute`` (the pp-1 → 0 edge carries the
chunk-boundary handoff); finished micro-batches surface on device 0's receive
port at ``j == 0`` ticks. Backward = autodiff reversing the scan (GPipe
ordering); activation footprint is that of the forward scan, reduced per
layer by the usual remat strategies.
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes
from galvatron_tpu.parallel.sharding import param_spec


def validate_interleaved_strategies(cfg: ModelConfig, hp: HybridParallelConfig) -> int:
    """Check the stacking constraint; returns layers per *virtual* stage.

    All virtual stages share one (pp, vpp)-stacked param array per position,
    hence one sharding: layer strategies must repeat with period
    ``num_layers / (pp*vpp)`` across the whole model."""
    L, pp, vpp = cfg.num_layers, hp.pp, hp.vpp
    if L % (pp * vpp) != 0:
        raise ValueError(f"pp*vpp={pp * vpp} must divide the layer count {L}")
    lpvs = L // (pp * vpp)
    for q in range(lpvs):
        base = hp.layer_strategies[q]
        for k in range(1, pp * vpp):
            other = hp.layer_strategies[k * lpvs + q]
            if other != base:
                raise ValueError(
                    f"interleaved schedule: layers at virtual-stage position {q} "
                    f"must share one strategy across all {pp * vpp} virtual "
                    f"stages (virtual stage 0 has {base}, {k} has {other})"
                )
    return lpvs


def init_interleaved_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """Param tree: embed/final_norm/head as in the plain pipeline;
    ``vstages[q]`` = position-q layer params stacked (pp, vpp, ...) — entry
    [s, j] belongs to layer ``(s + j·pp)·lpvs + q``."""
    from galvatron_tpu.parallel.pipeline import base_model_params

    lpvs = validate_interleaved_strategies(cfg, hp)
    pp, vpp = hp.pp, hp.vpp
    ks = jax.random.split(key, 4)
    base = base_model_params(ks, cfg)
    layer_keys = jax.random.split(ks[3], cfg.num_layers)
    vstages = []
    for q in range(lpvs):
        keys_q = jnp.stack(
            [
                jnp.stack([layer_keys[(s + j * pp) * lpvs + q] for j in range(vpp)])
                for s in range(pp)
            ]
        )  # (pp, vpp, key)
        vstages.append(
            jax.vmap(jax.vmap(lambda k: modeling.init_layer_params(k, cfg)))(keys_q)
        )
    base["vstages"] = vstages
    return base


def interleaved_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    """vstages[q] leaves get P('pp', None, *strategy_q_spec) — the vpp dim is
    replicated-by-stacking (each [s, j] slice is a distinct layer's params);
    embed/head/norm identical to the plain pipeline."""
    from galvatron_tpu.parallel.pipeline import pipeline_param_specs

    lpvs = cfg.num_layers // (hp.pp * hp.vpp)
    annots = modeling.layer_annotations(cfg)
    is_leaf = lambda x: hasattr(x, "shape")
    # embed/head/norm: reuse the plain-pipeline spec builder on a shape tree
    # without the layer stacks
    other_shape = {k: v for k, v in params_shape.items() if k != "vstages"}
    specs = pipeline_param_specs(other_shape, cfg, hp, axes, for_opt_state=for_opt_state)
    specs["vstages"] = []
    for q in range(lpvs):
        s_q = hp.layer_strategies[q]
        specs["vstages"].append(
            jax.tree.map(
                lambda leaf, a: P(
                    "pp", None,
                    *param_spec(leaf.shape[2:], a, axes, s_q, for_opt_state=for_opt_state),
                ),
                params_shape["vstages"][q],
                annots,
                is_leaf=is_leaf,
            )
        )
    return specs


def interleaved_pipeline(block_fn, pp: int, vpp: int, chunks: int, mesh: Mesh):
    """Returns f(vstage_params_local, x_mbs) -> ys for a manual-'pp' shard_map.
    ``ys`` is (1, chunks, mb, S, H) locally; globally stacked over pp with the
    real outputs in the pp=0 slice (finished micro-batches surface at device
    0's receive port)."""

    ring = [(i, (i + 1) % pp) for i in range(pp)]
    n_total = vpp * chunks
    T = n_total + pp

    def run(vstage_params, x_mbs):
        # strip the size-1 local 'pp' stacking dim → leaves (vpp, ...)
        vstage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), vstage_params)
        s = jax.lax.axis_index("pp")
        mb_shape = x_mbs.shape[1:]
        send0 = jnp.zeros(mb_shape, x_mbs.dtype)
        # chunks real slots + one sacrificial slot for invalid-tick writes
        ys0 = jnp.zeros((chunks + 1,) + mb_shape, x_mbs.dtype)

        def tick(carry, t):
            send, ys = carry
            recv = jax.lax.ppermute(send, "pp", ring)
            n = t - s
            nc = jnp.maximum(n, 0)  # decomposition below needs n >= 0
            r = jnp.mod(nc, pp)
            q2 = nc // pp
            j = jnp.mod(q2, vpp)
            g = q2 // vpp
            m = g * pp + r
            first_in = jax.lax.dynamic_index_in_dim(
                x_mbs, jnp.clip(m, 0, chunks - 1), keepdims=False
            )
            x_in = jnp.where((s == 0) & (j == 0), first_in, recv)
            params_j = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j, 0, keepdims=False),
                vstage_params,
            )
            out = block_fn(params_j, x_in)
            # capture: on device 0 a j==0 tick's incoming value is the finished
            # output of micro-batch m - pp (sent by device pp-1, virtual chunk
            # vpp-1, one tick earlier)
            m_out = m - pp
            cap = (s == 0) & (j == 0) & (m_out >= 0) & (m_out < chunks) & (n >= 0)
            slot = jnp.where(cap, jnp.clip(m_out, 0, chunks - 1), chunks)
            ys = jax.lax.dynamic_update_index_in_dim(ys, recv, slot, 0)
            return (out, ys), None

        (send, ys), _ = jax.lax.scan(tick, (send0, ys0), jnp.arange(T))
        return ys[None, :chunks]

    return run
