"""Interleaved (virtual-pipeline-stage) schedule over the pp ring.

TPU-native rendering of the interleaved schedule the reference carries only
in its vendored Megatron (core/pipeline_parallel/schedules.py:367,
``--num-layers-per-virtual-pipeline-stage``) and never wires into Galvatron's
own engine (SURVEY §2.3 'PP' row). Here it is first-class: the model is cut
into ``vpp * pp`` *virtual stages*; device ``s`` holds virtual stages
``{s, s+pp, ..., s+(vpp-1)·pp}``, so each micro-batch travels the device ring
``vpp`` times. Ticks are one virtual stage long (1/vpp of a physical stage),
shrinking the pipeline-fill bubble from ``(pp-1)·T/pp`` to ``(pp-1)·T/(pp·vpp)``
— the same bubble/vpp factor as Megatron's interleaved 1F1B.

Schedule (all static arithmetic, one ``lax.scan``): micro-batches flow in
groups of ``pp`` (hence ``chunks % pp == 0``, the reference's own interleaved
constraint). At tick ``t`` device ``s`` computes virtual chunk ``j`` of
micro-batch ``m`` where, with ``n = t - s``::

    r = n mod pp;  q = n div pp;  j = q mod vpp;  g = q div vpp;  m = g·pp + r

This is a bijection (r, j, g) ↔ n, so every device is busy every tick of
``[s, s + vpp·chunks)`` — the only idle ticks are the ``pp-1``-tick ramp.
Sends ride one ring ``ppermute`` (the pp-1 → 0 edge carries the
chunk-boundary handoff); finished micro-batches surface on device 0's receive
port at ``j == 0`` ticks. Backward = autodiff reversing the scan (GPipe
ordering); activation footprint is that of the forward scan, reduced per
layer by the usual remat strategies.
"""

from __future__ import annotations

from typing import Any, List

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes
from galvatron_tpu.parallel.sharding import param_spec


def validate_interleaved_strategies(cfg: ModelConfig, hp: HybridParallelConfig) -> int:
    """Check the stacking constraint; returns layers per *virtual* stage.

    All virtual stages share one (pp, vpp)-stacked param array per position,
    hence one sharding: layer strategies must repeat with period
    ``num_layers / (pp*vpp)`` across the whole model."""
    L, pp, vpp = cfg.num_layers, hp.pp, hp.vpp
    if L % (pp * vpp) != 0:
        raise ValueError(f"pp*vpp={pp * vpp} must divide the layer count {L}")
    lpvs = L // (pp * vpp)
    for q in range(lpvs):
        base = hp.layer_strategies[q]
        for k in range(1, pp * vpp):
            other = hp.layer_strategies[k * lpvs + q]
            if other != base:
                raise ValueError(
                    f"interleaved schedule: layers at virtual-stage position {q} "
                    f"must share one strategy across all {pp * vpp} virtual "
                    f"stages (virtual stage 0 has {base}, {k} has {other})"
                )
    return lpvs


def init_interleaved_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """Param tree: embed/final_norm/head as in the plain pipeline;
    ``vstages[q]`` = position-q layer params stacked (pp, vpp, ...) — entry
    [s, j] belongs to layer ``(s + j·pp)·lpvs + q``."""
    from galvatron_tpu.parallel.pipeline import base_model_params

    lpvs = validate_interleaved_strategies(cfg, hp)
    pp, vpp = hp.pp, hp.vpp
    ks = jax.random.split(key, 4)
    base = base_model_params(ks, cfg)
    layer_keys = jax.random.split(ks[3], cfg.num_layers)
    vstages = []
    for q in range(lpvs):
        keys_q = jnp.stack(
            [
                jnp.stack([layer_keys[(s + j * pp) * lpvs + q] for j in range(vpp)])
                for s in range(pp)
            ]
        )  # (pp, vpp, key)
        vstages.append(
            jax.vmap(jax.vmap(lambda k: modeling.init_layer_params(k, cfg)))(keys_q)
        )
    base["vstages"] = vstages
    return base


def restack_flat_vstages(flat_params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Flat ``layers`` list → the ``vstages[q]`` (pp, vpp) stacks; entry
    [s, j] is layer (s + j·pp)·lpvs + q (shared by the gpipe-ordered and
    1F1B interleaved engines)."""
    pp, vpp = hp.pp, hp.vpp
    lpvs = cfg.num_layers // (pp * vpp)
    layers = flat_params["layers"]
    params = {k: v for k, v in flat_params.items() if k != "layers"}
    params["vstages"] = [
        jax.tree.map(
            lambda *per_s: jnp.stack(per_s),
            *[
                jax.tree.map(
                    lambda *per_j: jnp.stack(per_j),
                    *[layers[(s + j * pp) * lpvs + q] for j in range(vpp)],
                )
                for s in range(pp)
            ],
        )
        for q in range(lpvs)
    ]
    return params


def flatten_vstages(params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Inverse of restack_flat_vstages (portable-checkpoint layout)."""
    pp, vpp = hp.pp, hp.vpp
    lpvs = cfg.num_layers // (pp * vpp)
    flat = {k: v for k, v in params.items() if k != "vstages"}
    layers = [None] * cfg.num_layers
    for q in range(lpvs):
        for s in range(pp):
            for j in range(vpp):
                layers[(s + j * pp) * lpvs + q] = jax.tree.map(
                    lambda a, s_=s, j_=j: a[s_, j_], params["vstages"][q]
                )
    flat["layers"] = layers
    return flat


def interleaved_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    """vstages[q] leaves get P('pp', None, *strategy_q_spec) — the vpp dim is
    replicated-by-stacking (each [s, j] slice is a distinct layer's params);
    embed/head/norm identical to the plain pipeline."""
    from galvatron_tpu.parallel.pipeline import pipeline_param_specs

    lpvs = cfg.num_layers // (hp.pp * hp.vpp)
    annots = modeling.layer_annotations(cfg)
    is_leaf = lambda x: hasattr(x, "shape")
    # embed/head/norm: reuse the plain-pipeline spec builder on a shape tree
    # without the layer stacks
    other_shape = {k: v for k, v in params_shape.items() if k != "vstages"}
    specs = pipeline_param_specs(other_shape, cfg, hp, axes, for_opt_state=for_opt_state)
    specs["vstages"] = []
    for q in range(lpvs):
        s_q = hp.layer_strategies[q]
        specs["vstages"].append(
            jax.tree.map(
                lambda leaf, a: P(
                    "pp", None,
                    *param_spec(leaf.shape[2:], a, axes, s_q, for_opt_state=for_opt_state),
                ),
                params_shape["vstages"][q],
                annots,
                is_leaf=is_leaf,
            )
        )
    return specs


def interleaved_pipeline(block_fn, pp: int, vpp: int, chunks: int, mesh: Mesh):
    """Returns f(vstage_params_local, x_mbs) -> ys for a manual-'pp' shard_map.
    ``ys`` is (1, chunks, mb, S, H) locally; globally stacked over pp with the
    real outputs in the pp=0 slice (finished micro-batches surface at device
    0's receive port)."""

    ring = [(i, (i + 1) % pp) for i in range(pp)]
    n_total = vpp * chunks
    T = n_total + pp

    def run(vstage_params, x_mbs):
        # strip the size-1 local 'pp' stacking dim → leaves (vpp, ...)
        vstage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), vstage_params)
        s = jax.lax.axis_index("pp")
        mb_shape = x_mbs.shape[1:]
        send0 = jnp.zeros(mb_shape, x_mbs.dtype)
        # chunks real slots + one sacrificial slot for invalid-tick writes
        ys0 = jnp.zeros((chunks + 1,) + mb_shape, x_mbs.dtype)

        def tick(carry, t):
            send, ys = carry
            recv = jax.lax.ppermute(send, "pp", ring)
            n = t - s
            nc = jnp.maximum(n, 0)  # decomposition below needs n >= 0
            r = jnp.mod(nc, pp)
            q2 = nc // pp
            j = jnp.mod(q2, vpp)
            g = q2 // vpp
            m = g * pp + r
            first_in = jax.lax.dynamic_index_in_dim(
                x_mbs, jnp.clip(m, 0, chunks - 1), keepdims=False
            )
            x_in = jnp.where((s == 0) & (j == 0), first_in, recv)
            params_j = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j, 0, keepdims=False),
                vstage_params,
            )
            out = block_fn(params_j, x_in)
            # capture: on device 0 a j==0 tick's incoming value is the finished
            # output of micro-batch m - pp (sent by device pp-1, virtual chunk
            # vpp-1, one tick earlier)
            m_out = m - pp
            cap = (s == 0) & (j == 0) & (m_out >= 0) & (m_out < chunks) & (n >= 0)
            slot = jnp.where(cap, jnp.clip(m_out, 0, chunks - 1), chunks)
            ys = jax.lax.dynamic_update_index_in_dim(ys, recv, slot, 0)
            return (out, ys), None

        (send, ys), _ = jax.lax.scan(tick, (send0, ys0), jnp.arange(T))
        return ys[None, :chunks]

    return run


# ---------------------------------------------------------------------------
# Interleaved 1F1B (bounded-activation virtual stages)
# ---------------------------------------------------------------------------


def make_interleaved_1f1b_train_step(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam,
    global_batch_size: int,
    seq_len: int,
    block_fn,
):
    """Interleaved schedule with a hand-written 1F1B-style backward: live
    activations are bounded by the schedule depth (O(pp·vpp) micro-batch
    stashes per device), independent of ``chunks`` — the property the
    reference's vendored interleaved 1F1B provides (megatron
    core/pipeline_parallel/schedules.py:367) and its gpipe-ordered interleaved
    cousin here (``interleaved_pipeline``) lacks.

    Schedule (uniform SPMD clocked scan; all ticks run one masked forward AND
    one masked backward virtual-stage pass):

      forward  (device s, tick t):  n = t - s;            r = n mod pp;
                q = n div pp; j = q mod vpp; g = q div vpp; m = g·pp + r
      backward (device s, tick t):  n' = t - vpp·pp - (pp-1-s); with the same
                decomposition of n', j' = vpp-1 - (q' mod vpp), m' = g'·pp+r'

    i.e. the backward wave mirrors the forward wave (reversed device and
    virtual-stage order) at lag vpp·pp. Forward activations ride the wrapped
    up-ring; cotangents ride the wrapped down-ring, and each arrives exactly
    one tick before its consumer (the lag telescopes: t_b(m,j,s+1) =
    t_b(m,j,s) - 1 and t_b(m,j+1,0) = t_b(m,j,pp-1) - 1). Backward recomputes
    the virtual-stage forward from a stashed input ring buffer of
    min(chunks, 3·pp+1) slots per virtual stage (in-flight micro-batches per
    virtual stage span < 3 pp-groups at the vpp·pp lag).
    """
    from galvatron_tpu.core.optim import (
        adamw_update,
        apply_update_with_scaler,
        init_opt_state,
    )
    from galvatron_tpu.core.schedules import LossScalerConfig, init_scaler_state
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime
    from galvatron_tpu.parallel.pipeline import cpu_sim_compiler_options
    from galvatron_tpu.parallel.pipeline_1f1b import _head_loss
    from galvatron_tpu.parallel.sharding import constrain, sharding_tree
    from jax.sharding import NamedSharding

    pp, vpp, chunks = hp.pp, hp.vpp, max(1, hp.chunks)
    if global_batch_size % chunks:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks
    n_stash = min(chunks, 3 * pp + 1)
    n_static = mb * modeling.loss_tokens_per_sample(cfg, seq_len)
    T = vpp * chunks + vpp * pp + pp - 1
    up_ring = [(i, (i + 1) % pp) for i in range(pp)]
    down_ring = [(i, (i - 1) % pp) for i in range(pp)]
    head_keys = ("final_norm", "embed") if cfg.tie_word_embeddings else ("final_norm", "head")
    full_spec = P(("pp",) + axes.data_axes, None, None)

    def pipeline_body(vstage_params, head_sub, x_mbs, labels_mbs, scale):
        """shard_map(manual={'pp'}) body → per-stage-stacked (loss_sum, tok,
        d_vstages, d_head, dx_embed)."""
        vstage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), vstage_params)
        s = jax.lax.axis_index("pp")
        is_last = s == pp - 1
        is_first = s == 0
        act = x_mbs.shape[1:]  # (mb, S, H)
        f32 = lambda tree: jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        carry0 = {
            "fwd_send": jnp.zeros(act, x_mbs.dtype),
            "bwd_send": jnp.zeros(act, x_mbs.dtype),
            # per-virtual-stage input stash (+1 sacrificial slot)
            "stash": jnp.zeros((vpp, n_stash + 1) + act, x_mbs.dtype),
            "dw": f32(vstage_params),
            "dhead": f32(head_sub),
            "dx_embed": jnp.zeros((chunks + 1,) + act, jnp.float32),
            "loss_sum": jnp.zeros((), jnp.float32),
            "tok": jnp.zeros((), jnp.float32),
        }

        def decompose(n):
            nc = jnp.maximum(n, 0)
            r = jnp.mod(nc, pp)
            q = nc // pp
            return r, jnp.mod(q, vpp), q // vpp

        def tick(carry, t):
            recv_up = jax.lax.ppermute(carry["fwd_send"], "pp", up_ring)
            recv_dn = jax.lax.ppermute(carry["bwd_send"], "pp", down_ring)

            # ---- forward virtual-stage pass
            n_f = t - s
            r_f, j_f, g_f = decompose(n_f)
            m_f = jnp.clip(g_f * pp + r_f, 0, chunks - 1)
            fwd_valid = (n_f >= 0) & (n_f < vpp * chunks)
            first_in = jax.lax.dynamic_index_in_dim(x_mbs, m_f, keepdims=False)
            x_in = jnp.where(is_first & (j_f == 0), first_in, recv_up)
            params_jf = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j_f, 0, keepdims=False),
                vstage_params,
            )
            out = block_fn(params_jf, x_in)
            fwd_slot = jnp.where(fwd_valid, jnp.mod(m_f, n_stash), n_stash)
            stash = carry["stash"].at[j_f, fwd_slot].set(x_in)

            # ---- backward virtual-stage pass (mirrored wave at lag vpp*pp)
            n_b = t - vpp * pp - (pp - 1 - s)
            r_b, jj, g_b = decompose(n_b)
            j_b = vpp - 1 - jj
            m_b = jnp.clip(g_b * pp + r_b, 0, chunks - 1)
            bwd_valid = (n_b >= 0) & (n_b < vpp * chunks)
            x_saved = stash[j_b, jnp.mod(m_b, n_stash)]
            params_jb = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j_b, 0, keepdims=False),
                vstage_params,
            )
            out_rec, f_vjp = jax.vjp(block_fn, params_jb, x_saved)

            # head loss on the recomputed output of the LAST virtual stage
            labels = jax.lax.dynamic_index_in_dim(labels_mbs, m_b, keepdims=False)
            nll, head_vjp, cnt = jax.vjp(
                lambda hs, y: _head_loss(hs, y, labels, cfg), head_sub, out_rec,
                has_aux=True,
            )
            head_mask = (is_last & bwd_valid & (j_b == vpp - 1)).astype(jnp.float32)
            dhead_mb, dy_head = head_vjp(head_mask * scale / n_static)

            dy_in = jnp.where(is_last & (j_b == vpp - 1), dy_head, recv_dn)
            dy_in = jnp.where(bwd_valid, dy_in, jnp.zeros_like(dy_in))
            dw_mb, dx = f_vjp(dy_in.astype(x_mbs.dtype))

            emb_slot = jnp.where(bwd_valid & is_first & (j_b == 0), m_b, chunks)
            dx_embed = jax.lax.dynamic_update_index_in_dim(
                carry["dx_embed"], dx.astype(jnp.float32), emb_slot, 0
            )
            dw = jax.tree.map(
                lambda A, g: A.at[j_b].add(g.astype(jnp.float32)), carry["dw"], dw_mb
            )

            new_carry = {
                "fwd_send": out,
                "bwd_send": dx,
                "stash": stash,
                "dw": dw,
                "dhead": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dhead"], dhead_mb
                ),
                "dx_embed": dx_embed,
                "loss_sum": carry["loss_sum"] + nll * head_mask,
                "tok": carry["tok"] + cnt * head_mask,
            }
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        stack = lambda tree: jax.tree.map(lambda a: a[None], tree)
        return (
            carry["loss_sum"][None],
            carry["tok"][None],
            stack(carry["dw"]),
            stack(carry["dhead"]),
            carry["dx_embed"][None, :chunks],
        )

    body_sm = compat.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P()),
        out_specs=(P("pp"), P("pp"), P("pp"), P("pp"), P("pp")),
        axis_names={"pp"},
        check_vma=False,
    )

    def eval_body(vstage_params, head_sub, x_mbs, labels_mbs):
        """Forward-only interleaved wave (vpp*chunks + pp - 1 ticks): the
        head loss rides the forward output of the last virtual stage; no
        vjp/stash/grad machinery — eval at ~1/3 of train cost."""
        vstage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), vstage_params)
        s = jax.lax.axis_index("pp")
        is_last = s == pp - 1
        is_first = s == 0
        act = x_mbs.shape[1:]
        carry0 = {
            "fwd_send": jnp.zeros(act, x_mbs.dtype),
            "loss_sum": jnp.zeros((), jnp.float32),
            "tok": jnp.zeros((), jnp.float32),
        }

        def decompose(n):
            nc = jnp.maximum(n, 0)
            r = jnp.mod(nc, pp)
            q = nc // pp
            return r, jnp.mod(q, vpp), q // vpp

        def tick(carry, t):
            recv_up = jax.lax.ppermute(carry["fwd_send"], "pp", up_ring)
            n_f = t - s
            r_f, j_f, g_f = decompose(n_f)
            m_f = jnp.clip(g_f * pp + r_f, 0, chunks - 1)
            fwd_valid = (n_f >= 0) & (n_f < vpp * chunks)
            first_in = jax.lax.dynamic_index_in_dim(x_mbs, m_f, keepdims=False)
            x_in = jnp.where(is_first & (j_f == 0), first_in, recv_up)
            params_jf = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j_f, 0, keepdims=False),
                vstage_params,
            )
            out = block_fn(params_jf, x_in)
            labels = jax.lax.dynamic_index_in_dim(labels_mbs, m_f, keepdims=False)
            nll, cnt = _head_loss(head_sub, out, labels, cfg)
            head_mask = (is_last & fwd_valid & (j_f == vpp - 1)).astype(jnp.float32)
            return {
                "fwd_send": out,
                "loss_sum": carry["loss_sum"] + nll * head_mask,
                "tok": carry["tok"] + cnt * head_mask,
            }, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(vpp * chunks + pp - 1))
        return carry["loss_sum"][None], carry["tok"][None]

    eval_sm = compat.shard_map(
        eval_body,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=(P("pp"), P("pp")),
        axis_names={"pp"},
        check_vma=False,
    )

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def train_step(state, batch):
        params = state["params"]
        scale = state["scaler"]["scale"] if fp16 else jnp.ones((), jnp.float32)
        inputs, labels = modeling.split_batch(batch, cfg)
        head_sub = {k: params[k] for k in head_keys}

        def embed_fn(embed_params):
            x = modeling.embed_any(inputs, {"embed": embed_params}, cfg)
            return constrain(x, mesh, full_spec)

        x, embed_vjp = jax.vjp(embed_fn, params["embed"])
        x_mbs = x.reshape(chunks, mb, *x.shape[1:])
        labels_mbs = labels.reshape(chunks, mb, *labels.shape[1:])
        loss_s, tok_s, d_vstages, d_head_s, dx_embed_s = body_sm(
            params["vstages"], head_sub, x_mbs, labels_mbs, scale
        )
        loss_sum = loss_s[-1]
        tok = jnp.maximum(tok_s[-1], 1.0)
        d_head = jax.tree.map(lambda a: a[-1], d_head_s)
        dx_embed = dx_embed_s[0].reshape(global_batch_size, *x.shape[1:])
        (d_embed,) = embed_vjp(dx_embed.astype(x.dtype))

        grads = {"vstages": d_vstages, "embed": d_embed}
        for k in head_keys:
            if k == "embed":
                grads["embed"] = jax.tree.map(
                    lambda a, b: a.astype(jnp.float32) + b, grads["embed"], d_head["embed"]
                )
            else:
                grads[k] = d_head[k]
        gdenom = tok * scale / n_static
        grads = {k: jax.tree.map(lambda g: g / gdenom, v) for k, v in grads.items()}
        loss = loss_sum / tok

        if fp16:
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        new_params, new_opt = adamw_update(params, grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    def eval_loss(state, batch):
        params = state["params"]
        inputs, labels = modeling.split_batch(batch, cfg)
        head_sub = {k: params[k] for k in head_keys}
        x = constrain(modeling.embed_any(inputs, params, cfg), mesh, full_spec)
        loss_s, tok_s = eval_sm(
            params["vstages"], head_sub,
            x.reshape(chunks, mb, *x.shape[1:]),
            labels.reshape(chunks, mb, *labels.shape[1:]),
        )
        return loss_s[-1] / jnp.maximum(tok_s[-1], 1.0)

    def init_state(key):
        params = init_interleaved_params(key, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(flat_params):
        params = restack_flat_vstages(flat_params, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": interleaved_param_specs(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": interleaved_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": interleaved_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))
    copts = cpu_sim_compiler_options(mesh)
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        eval_loss,
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)
    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=lambda sp: flatten_vstages(sp, cfg, hp),
        restack_params=lambda fp: restack_flat_vstages(fp, cfg, hp),
    )
