"""1F1B (pipedream-flush) pipeline schedule with a hand-written backward.

The reference's pipedream_flush interleaves warmup forwards, steady-state
1F1B, and cooldown backwards to bound live activations at O(pp) micro-batches
per stage instead of GPipe's O(chunks) (reference:
galvatron/core/pipeline/pipeline.py:237-480; combined send/recv ops
:1076-1177; FSDP hook re-registration :392-404 — unnecessary here since JAX
grads are pure values).

SPMD formulation: one clocked scan over T = chunks + 2(pp-1) ticks inside a
manual-'pp' shard_map. On tick t, stage s:

  forward of micro-batch  m_f = t - s                (if 0 <= m_f < chunks)
  backward of micro-batch m_b = t - 2(pp-1) + s      (if 0 <= m_b < chunks)

so the last stage runs fwd(m) and bwd(m) in the same tick (loss is computed
in-pipeline), and stage s holds at most 2(pp-1-s)+1 in-flight micro-batches.
Backward recomputes the stage forward from a stashed input ring buffer of
min(chunks, 2(pp-1)+1) slots via jax.vjp — 1F1B-with-recompute, the natural
XLA-static-shape rendering of the schedule.

Forward activations ride ppermute s→s+1; cotangents ride ppermute s→s-1 —
both deterministic, replacing the deadlock-avoidance machinery of the NCCL
engine (reference pipeline.py:373-378,966-968).
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import LossScalerConfig, init_scaler_state
from galvatron_tpu.core.strategy import HybridParallelConfig
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes
from galvatron_tpu.parallel.pipeline import cpu_sim_compiler_options
from galvatron_tpu.parallel.sharding import constrain, sharding_tree


def _head_loss(head_sub, y, labels, cfg: ModelConfig):
    """Final norm + output head + summed loss for one micro-batch; returns
    (nll_sum, aux=count). Dispatches per objective (LM / masked-LM labels are
    prepared by modeling.split_batch; 'cls' pools and classifies)."""
    y = modeling.norm(y, head_sub["final_norm"], cfg)
    if cfg.objective == "cls":
        s, n = modeling.cross_entropy_sum(
            modeling.cls_head(y, head_sub, cfg), labels, remat=modeling.ce_remat(cfg)
        )
        return s, n.astype(jnp.float32)
    if cfg.tie_word_embeddings:
        w = head_sub["embed"]["tok"].astype(y.dtype).T
    else:
        w = head_sub["head"]["w"].astype(y.dtype)
    logits = y @ w
    s, n = modeling.cross_entropy_sum(logits, labels, remat=modeling.ce_remat(cfg))
    return s, n.astype(jnp.float32)


def pipedream_schedule_ticks(pp: int, chunks: int):
    """Structural clock model of the 1F1B schedule, for the observability
    timeline (obs.tracing.emit_tick_spans). Mirrors the validity arithmetic
    of ``tick`` below exactly: on tick t stage s forwards micro-batch
    ``t - s`` and backwards ``t - 2(pp-1) + s`` when those indices are in
    range — so the warmup ramp, the steady 1F1B interleave, and the cooldown
    bubbles render from the same formulas the jitted scan executes. Returns
    ``(ticks, total_ticks)``; a (stage, tick) cell with no record is a
    pipeline bubble (visible as a gap on that stage's track)."""
    T = chunks + 2 * (pp - 1)
    ticks = []
    for s in range(pp):
        for t in range(T):
            m_f = t - s
            if 0 <= m_f < chunks:
                ticks.append({"stage": s, "tick": t, "kind": "fwd", "mb": m_f})
            m_b = t - 2 * (pp - 1) + s
            if 0 <= m_b < chunks:
                ticks.append({"stage": s, "tick": t, "kind": "bwd", "mb": m_b})
    return ticks, T


def make_1f1b_train_step(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam: AdamConfig,
    global_batch_size: int,
    seq_len: int,
    stage_fn,
):
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime
    from galvatron_tpu.parallel.pipeline import (
        flatten_stacked_layers,
        init_pipeline_params,
        pipeline_param_specs,
        restack_flat_layers,
    )

    pp, chunks = hp.pp, max(1, hp.chunks)
    if global_batch_size % chunks != 0:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks
    n_stash = min(chunks, 2 * (pp - 1) + 1)
    # loss-carrying positions per micro-batch (fp16-safe cotangent seeding)
    n_static = (global_batch_size // chunks) * modeling.loss_tokens_per_sample(cfg, seq_len)
    T = chunks + 2 * (pp - 1)
    up_perm = [(i, i + 1) for i in range(pp - 1)]
    down_perm = [(i + 1, i) for i in range(pp - 1)]
    head_keys = ("final_norm", "embed") if cfg.tie_word_embeddings else ("final_norm", "head")
    full_spec = P(("pp",) + axes.data_axes, None, None)

    packed = cfg.pack_sequences

    def pipeline_body(stage_params, head_sub, x_mbs, labels_mbs, scale, seg_mbs=None):
        """Runs under shard_map(manual={'pp'}). Returns per-stage-stacked
        (loss_sum, tok_count, d_stages, d_head, dx_embed). ``scale`` seeds the
        backward cotangent (fp16 loss scaling; 1.0 otherwise) so in-flight
        fp16 cotangents stay in range — all weight grads come back scaled.

        ``seg_mbs`` ((chunks, mb, S), packed sequences): segment ids per
        micro-batch, replicated over pp — the schedule's index arithmetic
        names the micro-batch each stage computes (fwd ``t − s``, bwd
        ``t − 2(pp−1) + s``), so forward AND the recompute-backward index the
        replicated array directly; no seg stash ring is needed."""
        # strip the size-1 local stage dim from the pp-stacked params
        stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
        stage = jax.lax.axis_index("pp")
        is_last = stage == pp - 1
        is_first = stage == 0
        act = x_mbs.shape[1:]  # (mb, S, H)
        f32 = lambda tree: jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        # SPMD discipline: every stage executes the SAME ops every tick —
        # collectives inside stage/head compute (TP psums, loss reductions,
        # ZeRO gathers) would deadlock under stage-varying lax.cond, so
        # validity is expressed by masking and by routing invalid writes to a
        # sacrificial extra slot (index n_stash / chunks) of each buffer.
        carry0 = {
            "fwd_send": jnp.zeros(act, x_mbs.dtype),
            "bwd_send": jnp.zeros(act, x_mbs.dtype),
            "stash": jnp.zeros((n_stash + 1,) + act, x_mbs.dtype),
            "dw": f32(stage_params),
            "dhead": f32(head_sub),
            "dx_embed": jnp.zeros((chunks + 1,) + act, jnp.float32),
            "loss_sum": jnp.zeros((), jnp.float32),
            "tok": jnp.zeros((), jnp.float32),
        }

        def tick(carry, t):
            prev_up = jax.lax.ppermute(carry["fwd_send"], "pp", up_perm)
            prev_dn = jax.lax.ppermute(carry["bwd_send"], "pp", down_perm)

            m_f = t - stage
            fwd_valid = (m_f >= 0) & (m_f < chunks)
            m_b = t - 2 * (pp - 1) + stage
            bwd_valid = (m_b >= 0) & (m_b < chunks)
            mf_c = jnp.clip(m_f, 0, chunks - 1)
            mb_c = jnp.clip(m_b, 0, chunks - 1)

            x_in = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(x_mbs, mf_c, keepdims=False), prev_up
            )

            # forward (unconditional; invalid ticks compute on garbage which
            # never reaches a valid consumer — see schedule proof in module doc)
            if seg_mbs is not None:
                seg_f = jax.lax.dynamic_index_in_dim(seg_mbs, mf_c, keepdims=False)
                out = stage_fn(stage_params, x_in, seg_f)
            else:
                out = stage_fn(stage_params, x_in)
            fwd_slot = jnp.where(fwd_valid, jnp.mod(mf_c, n_stash), n_stash)
            stash = jax.lax.dynamic_update_index_in_dim(carry["stash"], x_in, fwd_slot, 0)

            # head + loss cotangent (real only on the last stage's fwd ticks)
            labels = jax.lax.dynamic_index_in_dim(labels_mbs, mf_c, keepdims=False)
            nll, head_vjp, cnt = jax.vjp(
                lambda hs, y: _head_loss(hs, y, labels, cfg), head_sub, out, has_aux=True
            )
            head_mask = (is_last & fwd_valid).astype(jnp.float32)
            # seed normalized by the static micro-batch token count so the
            # scaled cotangents have mean-loss magnitude (a raw sum-loss seed
            # overflows fp16 at the initial 2^16 scale)
            dhead_mb, dy_head = head_vjp(head_mask * scale / n_static)

            # backward: recompute stage forward from the stashed input. Reads
            # the *updated* stash: the last stage backwards a micro-batch in
            # the same tick as its forward; for valid (fwd, bwd) pairs the
            # slots never collide (their index gap 2(pp-1-s) is < n_stash).
            x_saved = jax.lax.dynamic_index_in_dim(
                stash, jnp.mod(mb_c, n_stash), keepdims=False
            )
            dy_in = jnp.where(is_last, dy_head, prev_dn)
            dy_in = jnp.where(bwd_valid, dy_in, jnp.zeros_like(dy_in))
            if seg_mbs is not None:
                # the backward recompute must see the BACKWARD micro-batch's
                # segment ids (m_b ≠ m_f on interior ticks); closed over so
                # the vjp differentiates (params, x) only
                seg_b = jax.lax.dynamic_index_in_dim(seg_mbs, mb_c, keepdims=False)
                _, f_vjp = jax.vjp(
                    lambda p_, x_: stage_fn(p_, x_, seg_b), stage_params, x_saved
                )
            else:
                _, f_vjp = jax.vjp(stage_fn, stage_params, x_saved)
            dw_mb, dx = f_vjp(dy_in.astype(x_mbs.dtype))

            emb_slot = jnp.where(bwd_valid & is_first, mb_c, chunks)
            dx_embed = jax.lax.dynamic_update_index_in_dim(
                carry["dx_embed"], dx.astype(jnp.float32), emb_slot, 0
            )

            new_carry = {
                "fwd_send": out,
                "bwd_send": dx,
                "stash": stash,
                "dw": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dw"], dw_mb
                ),
                "dhead": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dhead"], dhead_mb
                ),
                "dx_embed": dx_embed,
                "loss_sum": carry["loss_sum"] + nll * head_mask,
                "tok": carry["tok"] + cnt * head_mask,
            }
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        stack = lambda tree: jax.tree.map(lambda a: a[None], tree)
        return (
            carry["loss_sum"][None],
            carry["tok"][None],
            stack(carry["dw"]),
            stack(carry["dhead"]),
            carry["dx_embed"][None, :chunks],
        )

    body_sm = compat.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P(), P()) if packed
        else (P("pp"), P(), P(), P(), P()),
        out_specs=(P("pp"), P("pp"), P("pp"), P("pp"), P("pp")),
        axis_names={"pp"},
        check_vma=False,
    )

    def eval_body(stage_params, head_sub, x_mbs, labels_mbs, seg_mbs=None):
        """Forward-only clocked schedule (chunks + pp - 1 ticks): no vjp, no
        stash ring, no gradient accumulators — eval at ~1/3 of train cost."""
        stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
        stage = jax.lax.axis_index("pp")
        is_last = stage == pp - 1
        is_first = stage == 0
        act = x_mbs.shape[1:]
        carry0 = {
            "fwd_send": jnp.zeros(act, x_mbs.dtype),
            "loss_sum": jnp.zeros((), jnp.float32),
            "tok": jnp.zeros((), jnp.float32),
        }

        def tick(carry, t):
            prev_up = jax.lax.ppermute(carry["fwd_send"], "pp", up_perm)
            m_f = t - stage
            fwd_valid = (m_f >= 0) & (m_f < chunks)
            mf_c = jnp.clip(m_f, 0, chunks - 1)
            x_in = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(x_mbs, mf_c, keepdims=False), prev_up
            )
            if seg_mbs is not None:
                seg_f = jax.lax.dynamic_index_in_dim(seg_mbs, mf_c, keepdims=False)
                out = stage_fn(stage_params, x_in, seg_f)
            else:
                out = stage_fn(stage_params, x_in)
            labels = jax.lax.dynamic_index_in_dim(labels_mbs, mf_c, keepdims=False)
            nll, cnt = _head_loss(head_sub, out, labels, cfg)
            head_mask = (is_last & fwd_valid).astype(jnp.float32)
            return {
                "fwd_send": out,
                "loss_sum": carry["loss_sum"] + nll * head_mask,
                "tok": carry["tok"] + cnt * head_mask,
            }, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(chunks + pp - 1))
        return carry["loss_sum"][None], carry["tok"][None]

    eval_sm = compat.shard_map(
        eval_body,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P()) if packed else (P("pp"), P(), P(), P()),
        out_specs=(P("pp"), P("pp")),
        axis_names={"pp"},
        check_vma=False,
    )

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def train_step(state, batch):
        params = state["params"]
        scale = state["scaler"]["scale"] if fp16 else jnp.ones((), jnp.float32)
        inputs, labels = modeling.split_batch(batch, cfg)
        head_sub = {k: params[k] for k in head_keys}
        if packed:
            tokens, seg, pos_ids = modeling.split_packed_inputs(inputs)
        else:
            tokens, seg, pos_ids = inputs, None, None

        # embedding forward (outside the pipelined section), with vjp capture
        def embed_fn(embed_params):
            if packed:
                x = modeling.embed(tokens, {"embed": embed_params}, cfg, pos_ids=pos_ids)
            else:
                x = modeling.embed_any(tokens, {"embed": embed_params}, cfg)
            return constrain(x, mesh, full_spec)

        x, embed_vjp = jax.vjp(embed_fn, params["embed"])
        x_mbs = x.reshape(chunks, mb, *x.shape[1:])
        labels_mbs = labels.reshape(chunks, mb, *labels.shape[1:])
        extra = (seg.reshape(chunks, mb, seg.shape[1]),) if packed else ()

        loss_s, tok_s, d_stages, d_head_s, dx_embed_s = body_sm(
            params["stages"], head_sub, x_mbs, labels_mbs, scale, *extra
        )
        loss_sum = loss_s[-1]
        tok = jnp.maximum(tok_s[-1], 1.0)
        d_head = jax.tree.map(lambda a: a[-1], d_head_s)
        dx_embed = dx_embed_s[0].reshape(global_batch_size, *x.shape[1:])
        (d_embed,) = embed_vjp(dx_embed.astype(x.dtype))

        # assemble the full gradient tree (mean over tokens)
        grads: Dict[str, Any] = {"stages": d_stages, "embed": d_embed}
        for k in head_keys:
            if k == "embed":  # tied head: add the in-pipeline contribution
                grads["embed"] = jax.tree.map(
                    lambda a, b: a.astype(jnp.float32) + b, grads["embed"], d_head["embed"]
                )
            else:
                grads[k] = d_head[k]
        gdenom = tok * scale / n_static  # unscale the seeded backward + token-mean
        grads = {k: jax.tree.map(lambda g: g / gdenom, v) for k, v in grads.items()}
        loss = loss_sum / tok

        if fp16:
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        new_params, new_opt = adamw_update(params, grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    def eval_loss(state, batch):
        params = state["params"]
        inputs, labels = modeling.split_batch(batch, cfg)
        head_sub = {k: params[k] for k in head_keys}
        if packed:
            tokens, seg, pos_ids = modeling.split_packed_inputs(inputs)
            x = modeling.embed(tokens, params, cfg, pos_ids=pos_ids)
            extra = (seg.reshape(chunks, mb, seg.shape[1]),)
        else:
            x = modeling.embed_any(inputs, params, cfg)
            extra = ()
        x = constrain(x, mesh, full_spec)
        loss_s, tok_s = eval_sm(
            params["stages"],
            head_sub,
            x.reshape(chunks, mb, *x.shape[1:]),
            labels.reshape(chunks, mb, *labels.shape[1:]),
            *extra,
        )
        return loss_s[-1] / jnp.maximum(tok_s[-1], 1.0)

    def init_state(key):
        params = init_pipeline_params(key, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(flat_params):
        params = restack_flat_layers(flat_params, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": pipeline_param_specs(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": pipeline_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": pipeline_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))

    copts = cpu_sim_compiler_options(mesh)
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        eval_loss,
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)

    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=lambda sp: flatten_stacked_layers(sp, cfg, hp),
        restack_params=lambda fp: restack_flat_layers(fp, cfg, hp),
    )
