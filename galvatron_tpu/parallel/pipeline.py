"""Pipeline-parallel engine: GPipe and 1F1B schedules over shard_map/ppermute.

TPU-native replacement for the reference's 1340-line NCCL pipeline engine
(galvatron/core/pipeline/pipeline.py). The mapping:

  reference                              → here
  PipelineParallel stage slicing (:75)   → stage-stacked params: every layer
                                           array gets a leading pp dim, spec
                                           P('pp', ...); inside the manual-pp
                                           shard_map each stage sees its slice
  chunk_batch microbatching (utils:9-36) → reshape to (chunks, mb, ...) — the
                                           ragged last chunk is disallowed
                                           (XLA static shapes; mirrors the
                                           search engine's strict-chunk filter,
                                           reference search_engine.py:196-198)
  _communicate / batch_isend_irecv p2p   → lax.ppermute along the 'pp' axis
    (:814-989, sync race guard :966-968)   (deterministic, no race class)
  gpipe_forward/backward (:497-629)      → clocked scan; jax.grad through the
                                           scan IS the reverse pipeline
  pipedream_flush 1F1B (:237-480)        → hand-written fwd+bwd clocked scan
                                           with O(pp) input stash + recompute
                                           (FSDP-hook surgery is unnecessary:
                                           grads are pure values)

Layout constraints under SPMD (documented deviations from the reference):
- uneven stage divisions (searched ``pp_division``) are supported via padded
  stacking: stacks are max(division) tall, light stages carry zero-filled
  masked padding slots (free in wall-clock — ticks are lockstep — and
  per-device memory is bounded by the heaviest stage regardless);
- layers at the same position within their stage share one strategy (stacked
  arrays have a single sharding). Per-position heterogeneity is retained;
  arbitrary per-layer heterogeneity is available at pp=1. Full cross-stage
  heterogeneity at pp>1 is a PRINCIPLED boundary of single-program SPMD, not
  an omission: a (pp, ...)-stacked parameter has exactly one sharding, and
  stage-varying shardings would need stage-varying GSPMD collectives inside
  the lockstep schedule — verified to deadlock (see pipeline_encdec.py,
  whose coupled-sub-pipeline design exists precisely to avoid it). Uneven
  divisions + per-position patterns recover most of the searched configs the
  reference emits (its per-layer choices cluster by stage position).
- embedding / final norm / LM head compute outside the pipelined section,
  sharded over the full mesh (pp included) on the batch dim; their params are
  replicated over pp (vocab-TP/ZeRO sharded per vocab strategy).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import (
    LossScalerConfig,
    init_scaler_state,
    scaled_value_and_grad,
)
from galvatron_tpu.core.strategy import (
    HybridParallelConfig,
    LayerStrategy,
    balanced_division,
)
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes, batch_spec, moe_token_axes
from galvatron_tpu.parallel.sharding import (
    constrain,
    cp_shard_axes,
    param_spec,
    sharding_tree,
    with_flash_shard_ctx,
    with_tp_overlap_ctx,
)

def cpu_sim_compiler_options(mesh=None):
    """XLA:CPU's all-reduce-promotion pass check-fails (CreateBinary with a
    copy opcode, hlo_instruction.cc:1585) on the copy-reduction all-reduces
    GSPMD emits for the sub-f32 pipeline backward — any bf16/fp16 GPipe or
    interleaved train step aborts the process on the CPU *simulation*. Real
    TPU backends never run that pass. Disable it per-compile on CPU only —
    keyed on the TARGET mesh's device platform (when given), not the
    process default backend: a TPU-topology AOT compile from a
    JAX_PLATFORMS=cpu process must NOT get the flag (it measurably changes
    the TPU buffer plan)."""
    if mesh is not None:
        try:
            platform = mesh.devices.flat[0].platform
        except Exception:
            platform = jax.default_backend()
        return {"xla_disable_hlo_passes": "all-reduce-promotion"} if platform == "cpu" else None
    if jax.default_backend() == "cpu":
        return {"xla_disable_hlo_passes": "all-reduce-promotion"}
    return None


# ---------------------------------------------------------------------------
# Stage-stacked parameters
# ---------------------------------------------------------------------------


def stage_layout(
    cfg: ModelConfig, hp: HybridParallelConfig
) -> Tuple[List[int], List[int], List[LayerStrategy]]:
    """(division, offsets, position_strategies) for the stage-stacked pipeline.

    Uneven divisions (the reference's searched ``pp_division``,
    galvatron/core/search_engine.py:586-654 / pipeline placement
    core/pipeline/pipeline.py:75-77) are realized by PADDED stacking: every
    stage's param stack carries ``max(division)`` positions; stages with fewer
    real layers carry zero-filled padding slots whose compute is masked out.
    Padding is free in wall-clock — the clocked schedules are lockstep, so
    tick time is set by the heaviest stage either way — and per-device memory
    is bounded by the heaviest stage regardless of padding.

    ``position_strategies[j]`` is the shared strategy of every real layer at
    stage position ``j`` (stacked arrays have one sharding, so layers at the
    same position must agree — checked here).
    """
    L, pp = cfg.num_layers, hp.pp
    div = list(hp.pp_division) if hp.pp_division else balanced_division(L, pp)
    if len(div) != pp or sum(div) != L or any(n < 1 for n in div):
        raise ValueError(
            f"pp_division {div} must have {pp} entries >= 1 summing to {L}"
        )
    offsets = list(np.cumsum([0] + div[:-1]))
    return div, offsets, position_strategies(hp.layer_strategies, div, offsets, "")


def position_strategies(
    strats: List[LayerStrategy], div: List[int], offsets: List[int], kind: str
) -> List[LayerStrategy]:
    """The shared per-position strategy of a padded stage stack: stacked
    arrays have one sharding, so real layers at the same stack position must
    agree across stages (the enc-dec layout applies this per sub-stack)."""
    pp = len(div)
    out: List[LayerStrategy] = []
    for j in range(max(div)):
        stages_with_j = [s for s in range(pp) if div[s] > j]
        ss = {strats[offsets[s] + j] for s in stages_with_j}
        if len(ss) > 1:
            raise ValueError(
                f"{kind + ' ' if kind else ''}layers at stage-position {j} "
                f"must share one strategy across stages "
                f"(got {sorted(map(str, ss))}); arbitrary per-layer "
                "heterogeneity is available at pp=1"
            )
        out.append(next(iter(ss)))
    return out


def validate_pipeline_strategies(cfg: ModelConfig, hp: HybridParallelConfig) -> int:
    """Check SPMD stacking constraints; returns positions-per-stage (the
    padded stack height, max of the stage division)."""
    div, _, pos = stage_layout(cfg, hp)
    return len(pos)


def base_model_params(ks, cfg: ModelConfig):
    """Non-layer params (embed / final_norm / head) shared by the pipeline
    engines. Vision (ViT) models get the patch-projection embedding + pooled
    class head; token models the vocab table (+ optional untied LM head)."""
    if cfg.image_size:
        if cfg.swin_depths:
            # Swin's merges are model-level params and its final_norm/head sit
            # at the widened c_last — the stage-stacked pipeline never supports
            # it (build_runtime rejects it first)
            raise ValueError("Swin models have no pipeline parameterization (pp=1 only)")
        return modeling.init_vision_base_params(ks[:3], cfg)
    base = {
        "embed": {
            "tok": jax.random.normal(ks[0], (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
            * 0.02
        },
        "final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
    }
    if cfg.pos_embed == "learned":
        base["embed"]["pos"] = (
            jax.random.normal(ks[1], (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype) * 0.02
        )
    if cfg.norm_type == "layernorm":
        base["final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
    if not cfg.tie_word_embeddings:
        base["head"] = {
            "w": modeling._dense_init(ks[2], cfg.hidden_size, cfg.vocab_size, cfg.param_dtype)
        }
    return base


def base_model_annots(cfg: ModelConfig):
    """Logical-axes annotations matching base_model_params."""
    if cfg.image_size:
        return modeling.vision_base_annotations(cfg)
    a = {
        "embed": {"tok": ("tp", "fsdp")},
        "final_norm": {"scale": ("fsdp",)},
    }
    if cfg.pos_embed == "learned":
        a["embed"]["pos"] = ("fsdp", None)
    if cfg.norm_type == "layernorm":
        a["final_norm"]["bias"] = ("fsdp",)
    if not cfg.tie_word_embeddings:
        a["head"] = {"w": ("fsdp", "tp")}
    return a


def restack_flat_layers(flat_params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Flat model tree (modeling.init_model_params layout) → the pp-stacked
    ``stages[j]`` layout of init_pipeline_params: stages[j][leaf] = stack over
    stage s of the stage's j-th layer (zero padding where a stage has fewer
    layers than max(division)). Shared by the GPipe and 1F1B runtimes'
    init_state_from (pretrained-weight adoption)."""
    div, offsets, pos = stage_layout(cfg, hp)
    layers = flat_params["layers"]
    params = {k: v for k, v in flat_params.items() if k != "layers"}
    zeros = jax.tree.map(jnp.zeros_like, layers[0])
    params["stages"] = [
        jax.tree.map(
            lambda *ls: jnp.stack(ls),
            *[
                layers[offsets[s] + j] if div[s] > j else zeros
                for s in range(hp.pp)
            ],
        )
        for j in range(len(pos))
    ]
    return params


def flatten_stacked_layers(params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Inverse of restack_flat_layers: ``stages[j]`` stacks → the flat
    ``layers`` list (padded slots dropped). Portable-checkpoint layout
    (core/checkpoint.py): checkpoints are always saved flat so resume works
    across pipeline degrees and schedules."""
    div, offsets, pos = stage_layout(cfg, hp)
    flat = {k: v for k, v in params.items() if k != "stages"}
    layers = [None] * cfg.num_layers
    for s_ in range(hp.pp):
        for j in range(div[s_]):
            layers[offsets[s_] + j] = jax.tree.map(
                lambda a, s__=s_: a[s__], params["stages"][j]
            )
    flat["layers"] = layers
    return flat


def init_pipeline_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """Param tree for pp>1: embed/final_norm/head as usual (replicated over pp);
    transformer layers as ``stages[j]`` — position-j layer params stacked over
    stages, leading dim pp; padding slots (uneven division) zero-filled."""
    div, offsets, pos = stage_layout(cfg, hp)
    ks = jax.random.split(key, 4)
    base = base_model_params(ks, cfg)
    layer_keys = jax.random.split(ks[3], cfg.num_layers)
    # stages[j][leaf] has shape (pp, *leaf_shape); stage s slice is the
    # stage's j-th layer (offsets[s]+j globally), zeroed where j >= div[s]
    stages = []
    for j in range(len(pos)):
        keys_j = jnp.stack(
            [layer_keys[offsets[s] + j if div[s] > j else 0] for s in range(hp.pp)]
        )
        stacked = jax.vmap(lambda k: modeling.init_layer_params(k, cfg))(keys_j)
        if any(div[s] <= j for s in range(hp.pp)):
            mask = np.array([div[s] > j for s in range(hp.pp)])
            stacked = jax.tree.map(
                lambda a: a * mask.reshape((hp.pp,) + (1,) * (a.ndim - 1)).astype(a.dtype),
                stacked,
            )
        stages.append(stacked)
    base["stages"] = stages
    return base


def pipeline_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    """Specs: stages[j] leaves get P('pp', *strategy_j_spec); embed/head/norm
    get the vocab strategy without a pp entry (replicated over pp)."""
    annots = modeling.layer_annotations(cfg)
    embed_strategy = LayerStrategy(
        tp=hp.vocab_tp, tp_consec=True, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    is_leaf = lambda x: hasattr(x, "shape")
    specs: Dict[str, Any] = {}
    model_annots = base_model_annots(cfg)
    for key in params_shape:
        if key == "stages":
            _, _, pos_strategies = stage_layout(cfg, hp)
            specs["stages"] = []
            for j in range(len(params_shape["stages"])):
                s_j = pos_strategies[j]
                specs["stages"].append(
                    jax.tree.map(
                        lambda leaf, a: P(
                            "pp",
                            *param_spec(
                                leaf.shape[1:], a, axes, s_j, for_opt_state=for_opt_state
                            ),
                        ),
                        params_shape["stages"][j],
                        annots,
                        is_leaf=is_leaf,
                    )
                )
        else:
            specs[key] = jax.tree.map(
                lambda leaf, a: param_spec(
                    leaf.shape, a, axes, embed_strategy, for_opt_state=for_opt_state
                ),
                params_shape[key],
                model_annots[key],
                is_leaf=is_leaf,
            )
    return specs


# ---------------------------------------------------------------------------
# Stage computation
# ---------------------------------------------------------------------------


def make_block_fn(
    cfg: ModelConfig,
    strategies: List[LayerStrategy],
    mesh: Mesh,
    axes: MeshAxes,
    active_counts: Optional[List[int]] = None,
):
    """Run ``len(strategies)`` decoder layers with per-position sharding
    constraints + remat (the per-layer wrap steps [3,5,6] of the reference
    construction, galvatron/core/hybrid_parallel_model.py:81-153). Used as one
    pipeline stage (gpipe/1F1B) or one virtual stage (interleaved).

    ``active_counts`` (uneven stage division): per-stage real-layer counts;
    position j acts as identity on stages where ``j >= active_counts[stage]``
    (padding slots of the stacked params). The masked select also zeroes the
    padding slots' gradients. Requires the 'pp' axis (shard_map manual).

    ``seg`` (packed sequences, cfg.pack_sequences): the (mb, S) segment ids of
    the micro-batch this stage is computing — rides beside the activations
    through the schedule (the clock index arithmetic selects it; see
    gpipe_pipeline / the 1F1B body) and drives the intra-segment attention
    mask + per-segment rope positions in every layer."""

    def act_spec(s: LayerStrategy) -> P:
        bs = batch_spec(axes, s)
        return P(bs[0], bs[1], None)

    def stage_fn(stage_params: List[Any], x, seg=None):
        if cfg.pos_embed == "rope":
            cos_sin = (
                modeling.packed_rope_tables(cfg, modeling.positions_from_segments(seg))
                if seg is not None
                else modeling.rope_tables(cfg, x.shape[1])
            )
        else:
            cos_sin = None
        alibi = (
            jnp.asarray(modeling.alibi_slopes(cfg.num_heads))
            if cfg.pos_embed == "alibi"
            else None
        )
        n_active = (
            None
            if active_counts is None
            else jnp.asarray(active_counts)[jax.lax.axis_index("pp")]
        )
        for j, s in enumerate(strategies):
            x = constrain(x, mesh, act_spec(s))
            layer_cfg = cfg
            if s.ckpt == "full" and cfg.mlp_recompute != "off":
                # full-layer remat subsumes the gate-save policy — same rule
                # as the pp=1 hook (hybrid._make_layer_hook)
                layer_cfg = layer_cfg.replace(mlp_recompute="off")
            if cfg.moe_experts > 0 and s.ep > 1:
                layer_cfg = layer_cfg.replace(
                    moe_shard_ctx=(
                        mesh,
                        axes.ep_axes(s.tp, s.tp_consec, s.ep),
                        moe_token_axes(axes, s),
                    )
                )
            if s.dp_type == "zero3" and s.tp > 1:
                # same fsdp x tp wgrad pin as the pp=1 hook — see
                # modeling._constrain_attn_out
                layer_cfg = layer_cfg.replace(
                    attn_out_shard_ctx=(mesh, axes.dp_axes(s.tp, s.tp_consec, s.cp))
                )
            layer_cfg = with_flash_shard_ctx(layer_cfg, s, mesh, axes)
            layer_cfg = with_tp_overlap_ctx(layer_cfg, s, mesh, axes)

            def run(x_, lp_):
                if s.cp > 1:
                    cp_axes = axes.cp_axes(s.tp, s.tp_consec, s.cp)
                    cp_kw = cp_shard_axes(s, axes)
                    # layer_cfg (not cfg): an MoE layer with cp>1 must keep
                    # its expert-dispatch sharding pins, as the pp=1 hook does
                    if s.cp_impl == "a2a":
                        from galvatron_tpu.parallel.ulysses import ulysses_decoder_layer

                        return ulysses_decoder_layer(
                            x_, lp_, layer_cfg, mesh, cp_axes, cos_sin, **cp_kw
                        )
                    from galvatron_tpu.parallel.ring import ring_decoder_layer

                    return ring_decoder_layer(
                        x_, lp_, layer_cfg, mesh, cp_axes, cos_sin, **cp_kw
                    )
                return modeling.decoder_layer(
                    x_, lp_, layer_cfg, cos_sin, alibi,
                    remat_attn=(s.ckpt == "selective"), seg_ids=seg,
                )

            if s.ckpt == "full":
                run = jax.checkpoint(run)
            out = run(x, stage_params[j])
            # identity on padding positions (and zero grads to their params)
            x = out if n_active is None else jnp.where(j < n_active, out, x)
        return x

    return stage_fn


def make_stage_fn(cfg: ModelConfig, hp: HybridParallelConfig, mesh: Mesh, axes: MeshAxes):
    """One physical pipeline stage: per-position strategies from the stage
    layout (stage_layout guarantees stages agree per position); uneven
    divisions mask the padding positions."""
    div, _, pos_strategies = stage_layout(cfg, hp)
    uneven = len(set(div)) > 1
    return make_block_fn(
        cfg, pos_strategies, mesh, axes, active_counts=div if uneven else None
    )


# ---------------------------------------------------------------------------
# GPipe schedule (clocked scan; autodiff = reverse pipeline)
# ---------------------------------------------------------------------------


def gpipe_pipeline(stage_fn, pp: int, chunks: int, mesh: Mesh, packed: bool = False):
    """Returns f(stage_params_local, x_mbs[, seg_mbs]) -> ys, to run under a
    manual-'pp' shard_map. Clock tick t: stage s computes micro-batch (t - s);
    forward sends ride ppermute s→s+1 (reference: gpipe_forward,
    galvatron/core/pipeline/pipeline.py:497-629).

    ``packed``: the run also takes ``seg_mbs`` (chunks, mb, S) segment ids,
    replicated over pp. Segment ids never ride the ppermute ring — the clock
    arithmetic says exactly which micro-batch stage s computes at tick t
    (``t - s``), so each stage indexes the replicated array directly."""

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def run(stage_params, x_mbs, seg_mbs=None):
        # x_mbs: (chunks, mb, S, H) replicated over pp.
        # P('pp')-sharded params keep a size-1 leading dim in the local view;
        # strip it so stage compute sees clean per-layer shapes.
        stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
        stage = jax.lax.axis_index("pp")
        mb_shape = x_mbs.shape[1:]
        state = jnp.zeros(mb_shape, x_mbs.dtype)
        ys = jnp.zeros((chunks,) + mb_shape, x_mbs.dtype)

        def tick(carry, t):
            state, ys = carry
            prev = jax.lax.ppermute(state, "pp", fwd_perm)
            mb_idx = jnp.clip(t, 0, chunks - 1)
            first_in = jax.lax.dynamic_index_in_dim(x_mbs, mb_idx, keepdims=False)
            x_in = jnp.where(stage == 0, first_in, prev)
            if seg_mbs is not None:
                # micro-batch THIS stage computes at tick t (invalid ticks
                # compute on garbage that never reaches a valid consumer,
                # exactly like the activations themselves)
                cur = jnp.clip(t - stage, 0, chunks - 1)
                seg = jax.lax.dynamic_index_in_dim(seg_mbs, cur, keepdims=False)
                out = stage_fn(stage_params, x_in, seg)
            else:
                out = stage_fn(stage_params, x_in)
            slot = jnp.clip(t - (pp - 1), 0, chunks - 1)
            ys = jax.lax.dynamic_update_index_in_dim(ys, out, slot, 0)
            return (out, ys), None

        (state, ys), _ = jax.lax.scan(tick, (state, ys), jnp.arange(chunks + pp - 1))
        # new leading stage axis so out_specs=P('pp') yields (pp, chunks, ...)
        # globally; only the pp=-1 slice holds real outputs
        return ys[None]

    if not packed:
        return lambda stage_params, x_mbs: run(stage_params, x_mbs)
    return run


def gpipe_schedule_ticks(pp: int, chunks: int):
    """Structural clock model of the GPipe train step, for the observability
    timeline (obs.tracing.emit_tick_spans): the schedule runs inside ONE
    jitted scan, so per-tick activity is not host-observable — this renders
    the exact index arithmetic the scan executes. Ticks ``0..chunks+pp-2``
    are the forward clock (stage s computes micro-batch ``t - s``, the
    ``tick`` function above); autodiff reverses it, so the backward occupies
    the mirrored clock shifted by one forward phase. Returns
    ``(ticks, total_ticks)`` with tick records {stage, tick, kind, mb}; a
    (stage, tick) cell with no record is a schedule bubble."""
    t_fwd = chunks + pp - 1
    ticks = []
    for s in range(pp):
        for m in range(chunks):
            ticks.append({"stage": s, "tick": m + s, "kind": "fwd", "mb": m})
            # reverse pipeline: last stage backwards mb chunks-1 first
            ticks.append({
                "stage": s, "tick": t_fwd + (chunks - 1 - m) + (pp - 1 - s),
                "kind": "bwd", "mb": m,
            })
    return ticks, 2 * t_fwd


# ---------------------------------------------------------------------------
# Runtime assembly
# ---------------------------------------------------------------------------


def build_pipeline_runtime(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam: AdamConfig,
    global_batch_size: int,
    seq_len: int,
):
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime

    pp, chunks = hp.pp, max(1, hp.chunks)
    if global_batch_size % chunks != 0:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks

    interleaved = hp.vpp > 1
    if interleaved:
        from galvatron_tpu.parallel.pipeline_interleaved import (
            flatten_vstages,
            init_interleaved_params,
            interleaved_param_specs,
            interleaved_pipeline,
            restack_flat_vstages,
            validate_interleaved_strategies,
        )

        lpvs = validate_interleaved_strategies(cfg, hp)
        block_fn = make_block_fn(cfg, hp.layer_strategies[:lpvs], mesh, axes)
        if hp.pipeline_type == "pipedream_flush":
            from galvatron_tpu.parallel.pipeline_interleaved import (
                make_interleaved_1f1b_train_step,
            )

            return make_interleaved_1f1b_train_step(
                cfg, hp, mesh, axes, adam, global_batch_size, seq_len, block_fn
            )
        pipe = interleaved_pipeline(block_fn, pp, hp.vpp, chunks, mesh)
        init_params_fn = lambda key: init_interleaved_params(key, cfg, hp)
        param_specs_fn = interleaved_param_specs
        out_stage = 0  # finished micro-batches surface on device 0
    else:
        validate_pipeline_strategies(cfg, hp)
        stage_fn = make_stage_fn(cfg, hp, mesh, axes)
        if hp.pipeline_type == "pipedream_flush":
            from galvatron_tpu.parallel.pipeline_1f1b import make_1f1b_train_step

            return make_1f1b_train_step(
                cfg, hp, mesh, axes, adam, global_batch_size, seq_len, stage_fn
            )

        pipe = gpipe_pipeline(stage_fn, pp, chunks, mesh, packed=cfg.pack_sequences)
        init_params_fn = lambda key: init_pipeline_params(key, cfg, hp)
        param_specs_fn = pipeline_param_specs
        out_stage = pp - 1  # last stage holds GPipe outputs
    packed = cfg.pack_sequences and not interleaved  # vpp>1 rejected upstream
    # full-batch spec for embedding/head compute: batch over pp + all data axes
    full_spec = P(("pp",) + axes.data_axes, None, None)

    pipe_sm = compat.shard_map(
        pipe,
        mesh=mesh,
        # stage params: pp-stacked; x_mbs (and packed seg_mbs) replicated
        in_specs=(P("pp"), P(), P()) if packed else (P("pp"), P()),
        out_specs=P("pp"),
        axis_names={"pp"},
        # vma tracking rejects with_sharding_constraint over auto axes inside
        # the manual region; disable it (grads still correct — probed)
        check_vma=False,
    )

    layer_params_key = "vstages" if interleaved else "stages"

    def loss_fn(params, batch):
        inputs, labels = modeling.split_batch(batch, cfg)
        if packed:
            tokens, seg, pos_ids = modeling.split_packed_inputs(inputs)
            x = modeling.embed(tokens, params, cfg, pos_ids=pos_ids)
        else:
            seg = None
            x = modeling.embed_any(inputs, params, cfg)
        x = constrain(x, mesh, full_spec)
        x_mbs = x.reshape(chunks, mb, *x.shape[1:])
        extra = (seg.reshape(chunks, mb, seg.shape[1]),) if packed else ()
        ys = pipe_sm(params[layer_params_key], x_mbs, *extra)  # (pp, chunks, mb, S, H)
        y = ys[out_stage].reshape(global_batch_size, *x.shape[1:])
        y = constrain(y, mesh, full_spec)
        y = modeling.norm(y, params["final_norm"], cfg)
        s, n = modeling.head_loss_sum(y, params, labels, cfg)
        return s / jnp.maximum(n, 1)

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def train_step(state, batch):
        if fp16:
            loss, grads = scaled_value_and_grad(loss_fn, state["scaler"]["scale"])(
                state["params"], batch
            )
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    def init_state(key):
        params = init_params_fn(key)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    restack = (
        (lambda fp: restack_flat_vstages(fp, cfg, hp))
        if interleaved
        else (lambda fp: restack_flat_layers(fp, cfg, hp))
    )
    flatten = (
        (lambda sp: flatten_vstages(sp, cfg, hp))
        if interleaved
        else (lambda sp: flatten_stacked_layers(sp, cfg, hp))
    )

    def state_from(flat_params):
        # flat model tree → the schedule's stacked layout
        params = restack(flat_params)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": param_specs_fn(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": param_specs_fn(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": param_specs_fn(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))

    copts = cpu_sim_compiler_options(mesh)
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        lambda state, batch: loss_fn(state["params"], batch),
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)

    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=flatten, restack_params=restack,
    )
