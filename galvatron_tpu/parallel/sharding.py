"""Per-layer parameter & optimizer-state sharding rules.

Replaces three reference subsystems with ``NamedSharding`` specs:

- Megatron TP layer wrappers (Column/RowParallelLinear with explicit tp_group;
  reference: site_package/megatron/core/tensor_parallel/layers.py:581,828) →
  weight dims annotated ``"tp"`` are sharded over the layer's TP axes;
- per-layer FSDP wrapping {ddp→NO_SHARD, zero2→SHARD_GRAD_OP, zero3→FULL_SHARD}
  (reference: galvatron/core/parallel.py:30-32,174-207) → dims annotated
  ``"fsdp"`` are sharded over the layer's DP axes for zero3 params and for
  zero2/zero3 optimizer state; XLA's GSPMD inserts the same all-gather /
  reduce-scatter pattern FSDP hand-schedules;
- activation redistribution between layers with different TP
  (reference: galvatron/core/redistribute.py) → ``with_sharding_constraint``
  at layer boundaries with each layer's ``batch_spec``.

Parameters are annotated with a *logical axes* tuple, one entry per dim, drawn
from {"tp", "fsdp", None}. ``"tp"`` marks a Megatron-sharded dim (column-
parallel output dim or row-parallel input dim); ``"fsdp"`` marks the dim ZeRO
shards (at most one per param is honored, the first divisible one).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import jax

from galvatron_tpu import compat
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.strategy import LayerStrategy
from galvatron_tpu.parallel.mesh import MeshAxes

Annotation = Tuple[Optional[str], ...]


def param_spec(
    shape: Sequence[int],
    annot: Annotation,
    axes: MeshAxes,
    s: LayerStrategy,
    *,
    for_opt_state: bool = False,
) -> P:
    """PartitionSpec for one parameter (or its Adam moment) under strategy ``s``.

    ZeRO semantics: zero3 shards params AND optimizer state over DP axes;
    zero2 shards only optimizer state (grad reduce-scatter + sharded update +
    param all-gather fall out of GSPMD); ddp shards neither.
    (reference: galvatron/core/parallel.py:30-32, cost-model ratio curves
    galvatron/core/cost_model.py:56-60)
    """
    if len(shape) != len(annot):
        raise ValueError(f"shape {shape} vs annotation {annot} rank mismatch")
    tp_ax = axes.tp_axes(s.tp, s.tp_consec)
    ep_ax = axes.ep_axes(s.tp, s.tp_consec, s.ep) if "ep" in annot else ()
    zero = s.dp_type == "zero3" or (for_opt_state and s.dp_type == "zero2")
    dp_ax = axes.dp_axes(s.tp, s.tp_consec, s.cp) if zero else ()
    # expert params are distinct per EP group: ZeRO shards them only over the
    # data axes *within* an EP group (reference: expert weights live on their
    # EP rank, parallel_state.py:611-621)
    dp_ax = tuple(a for a in dp_ax if a not in set(ep_ax))
    entries: list = []
    fsdp_used = False
    for dim, tag in zip(shape, annot):
        if tag == "tp" and tp_ax and dim % (2 ** len(tp_ax)) == 0:
            entries.append(tp_ax)
        elif tag == "ep" and ep_ax and dim % (2 ** len(ep_ax)) == 0:
            entries.append(ep_ax)
        elif tag == "fsdp" and dp_ax and not fsdp_used and dim % (2 ** len(dp_ax)) == 0:
            entries.append(dp_ax)
            fsdp_used = True
        else:
            entries.append(None)
    return P(*entries)


def spec_tree(
    params: Any,
    annots: Any,
    axes: MeshAxes,
    s: LayerStrategy,
    *,
    for_opt_state: bool = False,
) -> Any:
    """Map ``param_spec`` over a pytree of params and a matching tree of
    annotations (annotation leaves are tuples, so the annotation tree uses the
    param tree's structure with tuple leaves)."""
    return jax.tree.map(
        lambda p, a: param_spec(p.shape, a, axes, s, for_opt_state=for_opt_state),
        params,
        annots,
        is_leaf=lambda x: hasattr(x, "shape"),
    )


def sharding_tree(mesh: Mesh, specs: Any) -> Any:
    return jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), specs, is_leaf=lambda x: isinstance(x, P)
    )


def constrain(x, mesh: Mesh, spec: P):
    """``with_sharding_constraint`` under an explicit mesh — the activation-
    resharding boundary (replaces reference redistribute.py split/gather
    autograd functions; XLA emits the fused collective the reference's
    `_Fused_split_allgather` hand-writes).

    Inside a (partial-)manual shard_map region the constraint must be built
    on the tracing context's AbstractMesh (whose manual axes are typed
    Manual); the concrete mesh's sharding would be rejected in the
    transpose/grad path."""
    am = compat.get_abstract_mesh()
    target = am if (am is not None and not am.empty) else mesh
    return jax.lax.with_sharding_constraint(x, NamedSharding(target, spec))


def with_flash_shard_ctx(layer_cfg, s: LayerStrategy, mesh: Mesh, axes: MeshAxes):
    """Install ``flash_shard_ctx`` on a layer's ModelConfig for flash layers
    on multi-device meshes: GSPMD cannot partition Mosaic custom calls, so
    modeling._flash_shard_map must route each kernel invocation through a
    shard_map over the layer's (dp, tp) axes. One shared installer for every
    engine (pp=1 hook, make_block_fn, enc-dec sections) so the engines
    cannot diverge. cp>1 layers are excluded — the ring/ulysses paths carry
    their own shard_maps."""
    if (
        getattr(layer_cfg, "attn_impl", None) != "flash"
        or mesh.devices.size <= 1
        or s.cp > 1
    ):
        return layer_cfg
    return layer_cfg.replace(
        flash_shard_ctx=(
            mesh,
            axes.dp_axes(s.tp, s.tp_consec, s.cp),
            axes.tp_axes(s.tp, s.tp_consec),
        )
    )


def with_tp_overlap_ctx(layer_cfg, s: LayerStrategy, mesh: Mesh, axes: MeshAxes):
    """Install ``tp_overlap_ctx`` on a layer's ModelConfig when the plan sets
    ``tp_overlap`` (decomposed collective-matmul on the TP projection seams —
    see ops/collective_matmul.py and modeling._proj_up/_proj_down). Shared by
    every engine, like with_flash_shard_ctx. cp>1 layers are excluded — the
    ring/ulysses paths own their projection seams."""
    if (
        not getattr(s, "tp_overlap", False)
        or s.tp <= 1
        or mesh.devices.size <= 1
        or s.cp > 1
    ):
        return layer_cfg
    return layer_cfg.replace(
        tp_overlap_ctx=(
            mesh,
            axes.dp_axes(s.tp, s.tp_consec, s.cp),
            axes.tp_axes(s.tp, s.tp_consec),
            bool(s.sp),
        )
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _grad_shard(x, mesh, spec):
    return x


def _grad_shard_fwd(x, mesh, spec):
    return x, None


def _grad_shard_bwd(mesh, spec, _res, g):
    return (constrain(g, mesh, spec),)


_grad_shard.defvjp(_grad_shard_fwd, _grad_shard_bwd)


def overlap_grad_sync(params, annots, mesh: Mesh, axes: MeshAxes, s: LayerStrategy):
    """Async ZeRO gradient overlap: identity on ``params``, but each leaf's
    COTANGENT is pinned to its reduce-scattered (opt-state) sharding at the
    layer's point in the backward graph. Without the pin GSPMD is free to
    defer every zero2/zero3 gradient reduce-scatter to the jit output
    boundary — one trailing blob after the whole backward; with it, each
    layer's bucket is issued as its backward completes and overlaps the next
    layer's dgrad compute (the ZeRO overlap, Rajbhandari et al.). Applied by
    the pp=1 layer hook when HybridParallelConfig.grad_overlap is set."""
    if s.dp_type not in ("zero2", "zero3"):
        return params

    def leaf(p, a):
        spec = param_spec(p.shape, a, axes, s, for_opt_state=True)
        if all(e is None for e in spec):
            return p
        return _grad_shard(p, mesh, spec)

    return jax.tree.map(leaf, params, annots, is_leaf=lambda x: hasattr(x, "shape"))


def cp_shard_axes(s: LayerStrategy, axes: MeshAxes) -> dict:
    """(batch_axes, head_axes) kwargs for the ring/ulysses CP entries — one
    derivation shared by the pp=1 hook and the pipeline engines so they
    cannot diverge (companion of with_flash_shard_ctx)."""
    return dict(
        batch_axes=axes.dp_axes(s.tp, s.tp_consec, s.cp),
        head_axes=axes.tp_axes(s.tp, s.tp_consec),
    )
