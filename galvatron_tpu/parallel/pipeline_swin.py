"""Swin (hierarchical vision) pipeline: K coupled sections over the pp ring.

The reference pipelines its legacy swin branch by arbitrary per-stage layer
ranges (galvatron/core/hybrid_parallel_model.py:81-153); SPMD stage stacking
needs homogeneous pytrees per stack, and a Swin pyramid's stages have
DIFFERENT widths/resolutions, so this engine generalizes the enc-dec
coupled-sub-pipeline design (parallel/pipeline_encdec.py) from two sections
to K = len(swin_depths): device ``s`` holds a sub-stack of every section, and
every clocked tick runs section ``k`` on chunk ``t - k·pp - s`` — no
stage-diverging control flow (per-stage lax.cond around in-layer collectives
deadlocks under GSPMD), no steady-state waste.

Ring wiring: each section's output rides a WRAPPED ring (device pp-1 → 0);
within a section the wrap-free edges are the plain chain, and the wrap edge
delivers section k's finished output to device 0 exactly when that chunk
enters section k+1 there — device 0 applies the patch-merge projection
(replicated, token-local) to form the next section's input. The last useful
write is chunk chunks-1 at section K-1 on device pp-1 → T = chunks + K·pp - 1
ticks. Backward under ``pipeline_type='gpipe'`` is autodiff through the
clocked scan; ``'pipedream_flush'`` runs the hand-written coupled 1F1B below
(the enc-dec two-section 1F1B of pipeline_encdec.py generalized to K
sections), whose stash rings are bounded by the schedule depth instead of
growing with chunks.

Stacking unit = layer PAIR (plain + shifted window): Swin alternates the
window shift by position parity within a stage, so single-layer stacking
would give devices at different offsets different static shift programs —
pairs keep every stack position the same trace. Sections whose pair count is
smaller than pp leave zero-pair stages (masked to identity), so any
swin_depths pipeline at any pp >= 2 with even depths.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import (
    LossScalerConfig,
    init_scaler_state,
    scaled_value_and_grad,
)
from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes, batch_spec
from galvatron_tpu.parallel.pipeline import cpu_sim_compiler_options
from galvatron_tpu.parallel.sharding import constrain, param_spec, sharding_tree


def _spread_pairs(pairs: int, pp: int) -> List[int]:
    """Pairs over stages, zeros allowed (a section narrower than the ring
    leaves idle stages for that section); remainder placed by the same stage
    order as strategy.balanced_division so every section's maximum lands on
    the same stage."""
    base, rem = divmod(pairs, pp)
    div = [base] * pp
    order = sorted(range(pp), key=lambda s: (abs(s - (pp - 1) / 2), -s))
    for i in range(rem):
        div[order[i]] += 1
    return div


class SwinLayout:
    """Per-section pair-stack layout + per-pair-position strategies."""

    def __init__(self, cfg: ModelConfig, hp: HybridParallelConfig):
        depths = cfg.swin_depths
        pp = hp.pp
        if any(d % 2 for d in depths):
            raise ValueError(
                f"swin pipeline stacks layer PAIRS (plain+shifted) — depths "
                f"{depths} must all be even"
            )
        if hp.vpp > 1:
            raise ValueError("swin pipeline does not compose with vpp>1")
        if hp.pipeline_type not in ("gpipe", "pipedream_flush"):
            raise ValueError(
                "swin pipeline implements the coupled-sections schedule in "
                f"gpipe and pipedream_flush (1F1B) orderings (got "
                f"{hp.pipeline_type!r})"
            )
        # the layout derives its per-section divisions from swin_depths; a
        # user-provided pp_division that differs from the auto-filled
        # balanced default is rejected instead of silently ignored (the
        # enc-dec layout applies the same guard)
        from galvatron_tpu.core.strategy import balanced_division

        if hp.pp_division is not None and hp.pp_division != balanced_division(
            sum(depths), pp
        ):
            raise ValueError(
                f"swin pipeline derives stage divisions from swin_depths "
                f"{tuple(depths)} per section; a custom pp_division "
                f"({hp.pp_division}) is not honored"
            )
        self.K = len(depths)
        self.pp = pp
        self.base = list(np.cumsum([0] + [d for d in depths[:-1]]))  # layer idx base
        self.div = [_spread_pairs(d // 2, pp) for d in depths]
        self.off = [list(np.cumsum([0] + dv[:-1])) for dv in self.div]
        self.lpk = [max(dv) for dv in self.div]
        # strategy per (section, pair position): both pair layers and every
        # stage holding the position must agree (stacked arrays, one sharding)
        self.pos: List[List[LayerStrategy]] = []
        for k in range(self.K):
            sec: List[LayerStrategy] = []
            for q in range(self.lpk[k]):
                idxs = [
                    self.base[k] + 2 * (self.off[k][s] + q) + half
                    for s in range(pp)
                    if self.div[k][s] > q
                    for half in (0, 1)
                ]
                ss = {hp.layer_strategies[i] for i in idxs}
                if len(ss) > 1:
                    raise ValueError(
                        f"swin section {k} pair position {q}: the pair's "
                        f"layers must share one strategy across stages "
                        f"(got {sorted(map(str, ss))})"
                    )
                sec.append(next(iter(ss)))
            self.pos.append(sec)


def validate_swin_pipeline(cfg: ModelConfig, hp: HybridParallelConfig) -> SwinLayout:
    return SwinLayout(cfg, hp)


def _pair_tree(layers: List, i0: int):
    return {"a": layers[i0], "b": layers[i0 + 1]}


def init_swin_pipeline_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """Base (embed/final_norm/head) + merges replicated over pp;
    ``sections[k][q]`` = (pp, ...) stacks of PAIR params (zero padding on
    stages with fewer pairs)."""
    lay = validate_swin_pipeline(cfg, hp)
    flat = modeling.init_model_params(key, cfg)
    return restack_flat_swin(flat, cfg, hp, _lay=lay)


def restack_flat_swin(flat_params, cfg: ModelConfig, hp: HybridParallelConfig, _lay=None):
    lay = _lay or validate_swin_pipeline(cfg, hp)
    params = {k: v for k, v in flat_params.items() if k != "layers"}
    layers = flat_params["layers"]
    sections = []
    for k in range(lay.K):
        zeros = jax.tree.map(
            jnp.zeros_like, _pair_tree(layers, lay.base[k])
        )
        stacks = []
        for q in range(lay.lpk[k]):
            stacks.append(
                jax.tree.map(
                    lambda *ls: jnp.stack(ls),
                    *[
                        _pair_tree(layers, lay.base[k] + 2 * (lay.off[k][s] + q))
                        if lay.div[k][s] > q
                        else zeros
                        for s in range(lay.pp)
                    ],
                )
            )
        sections.append(stacks)
    params["sections"] = sections
    return params


def flatten_swin(params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Inverse of restack_flat_swin (padding dropped) — the portable flat
    ``layers`` checkpoint layout."""
    lay = validate_swin_pipeline(cfg, hp)
    flat = {k: v for k, v in params.items() if k != "sections"}
    layers: List[Any] = [None] * cfg.num_layers
    for k in range(lay.K):
        for s in range(lay.pp):
            for q in range(lay.div[k][s]):
                pair = jax.tree.map(lambda a, s_=s: a[s_], params["sections"][k][q])
                i0 = lay.base[k] + 2 * (lay.off[k][s] + q)
                layers[i0] = pair["a"]
                layers[i0 + 1] = pair["b"]
    flat["layers"] = layers
    return flat


def swin_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    lay = validate_swin_pipeline(cfg, hp)
    embed_strategy = LayerStrategy(
        tp=hp.vocab_tp, tp_consec=True, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    is_leaf = lambda x: hasattr(x, "shape")
    base_annots = modeling.vision_annotations(cfg)
    specs: Dict[str, Any] = {}
    for key in params_shape:
        if key == "sections":
            specs["sections"] = []
            for k in range(lay.K):
                lcfg = modeling.vision_layer_cfg(cfg, lay.base[k])
                pair_annots = {
                    "a": modeling.layer_annotations(lcfg),
                    "b": modeling.layer_annotations(lcfg),
                }
                specs["sections"].append(
                    [
                        jax.tree.map(
                            lambda leaf, a, q=q, k=k: P(
                                "pp",
                                *param_spec(
                                    leaf.shape[1:], a, axes, lay.pos[k][q],
                                    for_opt_state=for_opt_state,
                                ),
                            ),
                            params_shape["sections"][k][q],
                            pair_annots,
                            is_leaf=is_leaf,
                        )
                        for q in range(lay.lpk[k])
                    ]
                )
        else:
            specs[key] = jax.tree.map(
                lambda leaf, a: param_spec(
                    leaf.shape, a, axes, embed_strategy, for_opt_state=for_opt_state
                ),
                params_shape[key],
                base_annots[key],
                is_leaf=is_leaf,
            )
    return specs


def build_swin_pipeline_runtime(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam: AdamConfig,
    global_batch_size: int,
    seq_len: int,
):
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime

    pp, chunks = hp.pp, max(1, hp.chunks)
    if global_batch_size % chunks:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks
    lay = validate_swin_pipeline(cfg, hp)
    K = lay.K

    # per-section geometry + a representative pair of global layer indices
    # (every pair in a section is the same static program: stage geometry +
    # shift parity depend only on the section and the half)
    geom = [modeling.swin_geometry(cfg, k) for k in range(K)]  # (h, w, c, heads)
    sec_len = [g[0] * g[1] for g in geom]
    sec_c = [g[2] for g in geom]

    def act_spec(s: LayerStrategy) -> P:
        bs = batch_spec(axes, s)
        return P(bs[0], bs[1], None)

    def section_fn(k):
        i0 = lay.base[k]
        uneven = len(set(lay.div[k])) > 1 or min(lay.div[k]) == 0

        def run_section(stacks, x):
            n_active = (
                jnp.asarray(lay.div[k])[jax.lax.axis_index("pp")] if uneven else None
            )
            for q, s in enumerate(lay.pos[k]):
                x = constrain(x, mesh, act_spec(s))
                # full-layer remat subsumes the gate-save policy
                lcfg = (
                    cfg.replace(mlp_recompute="off")
                    if s.ckpt == "full" and cfg.mlp_recompute != "off"
                    else cfg
                )

                def pair(x_, pp_, lcfg=lcfg):
                    y = modeling.swin_layer(
                        x_, pp_["a"], lcfg, i0, remat_attn=(s.ckpt == "selective")
                    )
                    return modeling.swin_layer(
                        y, pp_["b"], lcfg, i0 + 1, remat_attn=(s.ckpt == "selective")
                    )

                if s.ckpt == "full":
                    pair = jax.checkpoint(pair)
                out = pair(x, stacks[q])
                x = out if n_active is None else jnp.where(q < n_active, out, x)
            return x

        return run_section

    section_fns = [section_fn(k) for k in range(K)]
    ring_wrap = [(i, (i + 1) % pp) for i in range(pp)]
    T = chunks + K * pp - 1
    full_spec = P(("pp",) + axes.data_axes, None, None)

    def pipeline(sections, merges, emb_mbs):
        """Manual-'pp' shard_map body → (1, chunks, mb, L_last, c_last)
        (real outputs in the pp-1 slice)."""
        sections = jax.tree.map(lambda a: jnp.squeeze(a, 0), sections)
        s = jax.lax.axis_index("pp")
        carry0 = {
            f"sec{k}": jnp.zeros((mb, sec_len[k], sec_c[k]), emb_mbs.dtype)
            for k in range(K)
        }
        carry0["ys"] = jnp.zeros(
            (chunks + 1, mb, sec_len[K - 1], sec_c[K - 1]), emb_mbs.dtype
        )

        def tick(carry, t):
            recv = [
                jax.lax.ppermute(carry[f"sec{k}"], "pp", ring_wrap) for k in range(K)
            ]
            new_carry = dict(carry)
            for k in range(K):
                m_k = jnp.clip(t - k * pp - s, 0, chunks - 1)
                if k == 0:
                    first_in = jax.lax.dynamic_index_in_dim(emb_mbs, m_k, keepdims=False)
                else:
                    # device 0 enters the chunk whose previous section just
                    # wrapped; patch-merge is replicated + token-local
                    first_in = modeling.patch_merge(recv[k - 1], merges[k - 1], cfg, k - 1)
                x_in = jnp.where(s == 0, first_in, recv[k])
                new_carry[f"sec{k}"] = section_fns[k](sections[k], x_in)
            m_last_raw = t - (K - 1) * pp - s
            valid = (m_last_raw >= 0) & (m_last_raw < chunks)
            slot = jnp.where(valid, jnp.clip(m_last_raw, 0, chunks - 1), chunks)
            new_carry["ys"] = jax.lax.dynamic_update_index_in_dim(
                carry["ys"], new_carry[f"sec{K - 1}"], slot, 0
            )
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        return carry["ys"][None, :chunks]

    pipe_sm = compat.shard_map(
        pipeline,
        mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=P("pp"),
        axis_names={"pp"},
        check_vma=False,
    )

    def loss_fn(params, batch):
        pixels, labels = modeling.split_batch(batch, cfg)
        x = modeling.vision_embed(pixels, params, cfg)
        x = constrain(x, mesh, full_spec)
        emb_mbs = x.reshape(chunks, mb, sec_len[0], sec_c[0])
        ys = pipe_sm(params["sections"], params["merges"], emb_mbs)
        y = ys[-1].reshape(global_batch_size, sec_len[K - 1], sec_c[K - 1])
        y = constrain(y, mesh, full_spec)
        y = modeling.norm(y, params["final_norm"], cfg)
        ssum, n = modeling.cross_entropy_sum(
            modeling.cls_head(y, params, cfg), labels, remat=modeling.ce_remat(cfg)
        )
        return ssum / jnp.maximum(n, 1)

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def gpipe_train_step(state, batch):
        if fp16:
            loss, grads = scaled_value_and_grad(loss_fn, state["scaler"]["scale"])(
                state["params"], batch
            )
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    # ------------------------------------------------------------------
    # 1F1B (pipedream_flush) ordering: the enc-dec coupled 1F1B
    # (pipeline_encdec.py) generalized to K sections. The coupled pipeline is
    # an interleaved virtual pipeline of depth K*pp (section k's virtual
    # stage s lives on device s), so the backward mirrors pipeline_1f1b: the
    # section-(K-1) backward wave starts at the last device in the SAME tick
    # as that chunk's final forward, each wave rides the down-chain, and at
    # device 0 the wave wraps (down-ring) to seed the previous section's
    # backward at device pp-1 one tick later. Backward recomputes each
    # section from stashed inputs — ring buffers bounded by the schedule
    # depth, independent of chunks (the 1F1B property the gpipe-ordered
    # autodiff backward lacks).
    #
    # Patch-merge placement flips versus the gpipe body: the SENDER merges
    # (every device computes section k then its merge; device 0 consumes the
    # wrapped, already-merged output) so every device's section-k input — and
    # therefore the one stash ring per section — has the uniform section-k
    # shape. The cotangent seed of the composed (section, merge) vjp is the
    # pair (dy_section, dy_merged): the down-chain recv fills the first on
    # s < pp-1, the down-ring wrap recv (device 0's section-(k+1) input
    # cotangent) fills the second on the last device; vjp linearity zeroes
    # the unused half. Numerically identical to merge-on-consumer (ppermute
    # is exact).
    #
    #   sec k fwd: m = t - k*pp - s
    #   sec k bwd: m = t - ((2K-k)*pp - 2) + s
    #   T = chunks + 2K*pp - 2;  stash[k]: min(chunks, 2*(K-k)*pp - 1)
    # ------------------------------------------------------------------
    from galvatron_tpu.parallel.pipeline_1f1b import _head_loss

    n_s = [min(chunks, 2 * (K - k) * pp - 1) for k in range(K)]
    off = [(2 * K - k) * pp - 2 for k in range(K)]
    T_1f1b = chunks + 2 * K * pp - 2
    n_static = mb  # loss-carrying positions per micro-batch (cls: one/sample)
    ring_wrap_down = [(i, (i - 1) % pp) for i in range(pp)]

    def sec_merge_fn(k):
        if k == K - 1:
            return section_fns[k]

        def f(stacks_k, merge_k, x):
            out = section_fns[k](stacks_k, x)
            return out, modeling.patch_merge(out, merge_k, cfg, k)

        return f

    sec_fns_1f1b = [sec_merge_fn(k) for k in range(K)]

    def pipeline_body_1f1b(sections, merges, head_sub, emb_mbs, labels_mbs, scale):
        sections = jax.tree.map(lambda a: jnp.squeeze(a, 0), sections)
        s = jax.lax.axis_index("pp")
        is_last = s == pp - 1
        is_first = s == 0
        dt = emb_mbs.dtype
        shp = [(mb, sec_len[k], sec_c[k]) for k in range(K)]
        f32 = lambda tree: jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        carry0 = {"loss_sum": jnp.zeros((), jnp.float32), "tok": jnp.zeros((), jnp.float32)}
        for k in range(K):
            carry0[f"f{k}"] = jnp.zeros(shp[k], dt)         # fwd send (wrapped ring)
            carry0[f"b{k}"] = jnp.zeros(shp[k], dt)         # bwd dx send (down ring)
            carry0[f"stash{k}"] = jnp.zeros((n_s[k] + 1,) + shp[k], dt)
            carry0[f"dw{k}"] = f32(sections[k])
            if k < K - 1:
                carry0[f"fm{k}"] = jnp.zeros(shp[k + 1], dt)  # merged send (wrap)
                carry0[f"dm{k}"] = f32(merges[k])
        carry0["dhead"] = f32(head_sub)
        carry0["dxe"] = jnp.zeros((chunks + 1,) + shp[0], jnp.float32)

        def tick(carry, t):
            rf = [jax.lax.ppermute(carry[f"f{k}"], "pp", ring_wrap) for k in range(K)]
            rfm = [
                jax.lax.ppermute(carry[f"fm{k}"], "pp", ring_wrap) for k in range(K - 1)
            ]
            rb = [
                jax.lax.ppermute(carry[f"b{k}"], "pp", ring_wrap_down) for k in range(K)
            ]
            new_carry = dict(carry)

            # ---- forwards (stash the section input, send out + merged out)
            for k in range(K):
                m_f = t - k * pp - s
                f_valid = (m_f >= 0) & (m_f < chunks)
                mf_c = jnp.clip(m_f, 0, chunks - 1)
                if k == 0:
                    first_in = jax.lax.dynamic_index_in_dim(emb_mbs, mf_c, keepdims=False)
                else:
                    first_in = rfm[k - 1]
                x_in = jnp.where(is_first, first_in, rf[k])
                slot = jnp.where(f_valid, jnp.mod(mf_c, n_s[k]), n_s[k])
                new_carry[f"stash{k}"] = jax.lax.dynamic_update_index_in_dim(
                    carry[f"stash{k}"], x_in, slot, 0
                )
                if k < K - 1:
                    out, mout = sec_fns_1f1b[k](sections[k], merges[k], x_in)
                    new_carry[f"fm{k}"] = mout
                else:
                    out = sec_fns_1f1b[k](sections[k], x_in)
                new_carry[f"f{k}"] = out

            # ---- backwards (recompute from the updated stash; the last
            # device backwards section K-1 of a chunk in the same tick as
            # its forward — for valid pairs the ring slots never collide)
            for k in range(K - 1, -1, -1):
                m_b = t - off[k] + s
                b_valid = (m_b >= 0) & (m_b < chunks)
                mb_c = jnp.clip(m_b, 0, chunks - 1)
                x_saved = jax.lax.dynamic_index_in_dim(
                    new_carry[f"stash{k}"], jnp.mod(mb_c, n_s[k]), keepdims=False
                )
                if k == K - 1:
                    out_rec, sec_vjp = jax.vjp(sec_fns_1f1b[k], sections[k], x_saved)
                    labels = jax.lax.dynamic_index_in_dim(
                        labels_mbs, mb_c, keepdims=False
                    )
                    nll, head_vjp, cnt = jax.vjp(
                        lambda hs, y: _head_loss(hs, y, labels, cfg),
                        head_sub, out_rec, has_aux=True,
                    )
                    head_mask = (is_last & b_valid).astype(jnp.float32)
                    dhead_mb, dy_head = head_vjp(head_mask * scale / n_static)
                    dy = jnp.where(is_last, dy_head, rb[k])
                    dy = jnp.where(b_valid, dy, jnp.zeros_like(dy))
                    dw_mb, dx = sec_vjp(dy.astype(dt))
                    new_carry["dhead"] = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), carry["dhead"], dhead_mb
                    )
                    new_carry["loss_sum"] = carry["loss_sum"] + nll * head_mask
                    new_carry["tok"] = carry["tok"] + cnt * head_mask
                else:
                    _, sec_vjp = jax.vjp(
                        sec_fns_1f1b[k], sections[k], merges[k], x_saved
                    )
                    dy_sec = jnp.where(
                        b_valid & jnp.logical_not(is_last), rb[k],
                        jnp.zeros_like(rb[k]),
                    )
                    dy_mout = jnp.where(
                        b_valid & is_last, rb[k + 1], jnp.zeros_like(rb[k + 1])
                    )
                    dw_mb, dmerge_mb, dx = sec_vjp((dy_sec.astype(dt), dy_mout.astype(dt)))
                    new_carry[f"dm{k}"] = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), carry[f"dm{k}"], dmerge_mb
                    )
                new_carry[f"dw{k}"] = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry[f"dw{k}"], dw_mb
                )
                new_carry[f"b{k}"] = dx.astype(dt)
                if k == 0:
                    new_carry["dxe"] = jax.lax.dynamic_update_index_in_dim(
                        carry["dxe"], dx.astype(jnp.float32),
                        jnp.where(b_valid & is_first, mb_c, chunks), 0,
                    )
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T_1f1b))
        stack = lambda tree: jax.tree.map(lambda a: a[None], tree)
        return (
            carry["loss_sum"][None],
            carry["tok"][None],
            stack([carry[f"dw{k}"] for k in range(K)]),
            stack([carry[f"dm{k}"] for k in range(K - 1)]),
            stack(carry["dhead"]),
            carry["dxe"][None, :chunks],
        )

    body_1f1b_sm = compat.shard_map(
        pipeline_body_1f1b,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P(), P()),
        out_specs=tuple([P("pp")] * 6),
        axis_names={"pp"},
        check_vma=False,
    )

    def train_step_1f1b(state, batch):
        params = state["params"]
        scale = state["scaler"]["scale"] if fp16 else jnp.ones((), jnp.float32)
        pixels, labels = modeling.split_batch(batch, cfg)
        head_sub = {"final_norm": params["final_norm"], "head": params["head"]}

        def embed_fn(embed_params):
            x = modeling.vision_embed(pixels, {"embed": embed_params}, cfg)
            return constrain(x, mesh, full_spec)

        x, embed_vjp = jax.vjp(embed_fn, params["embed"])
        emb_mbs = x.reshape(chunks, mb, sec_len[0], sec_c[0])
        labels_mbs = labels.reshape(chunks, mb)

        loss_s, tok_s, dw_s, dmerge_s, dhead_s, dxe_s = body_1f1b_sm(
            params["sections"], params["merges"], head_sub, emb_mbs, labels_mbs, scale
        )
        loss_sum = loss_s[-1]
        tok = jnp.maximum(tok_s[-1], 1.0)
        d_head = jax.tree.map(lambda a: a[-1], dhead_s)
        # merge grads are nonzero only where the wrap cotangent lands (the
        # last device) — sum the pp stack, like enc_final_norm in enc-dec
        d_merge = jax.tree.map(lambda a: a.sum(axis=0), dmerge_s)
        dxe_full = dxe_s[0].reshape(global_batch_size, sec_len[0], sec_c[0])
        (d_embed,) = embed_vjp(dxe_full.astype(x.dtype))

        grads: Dict[str, Any] = {
            "sections": dw_s,
            "merges": d_merge,
            "embed": d_embed,
            "final_norm": d_head["final_norm"],
            "head": d_head["head"],
        }
        gdenom = tok * scale / n_static
        grads = {k: jax.tree.map(lambda g: g / gdenom, v) for k, v in grads.items()}
        loss = loss_sum / tok

        if fp16:
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        new_params, new_opt = adamw_update(params, grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    train_step = (
        train_step_1f1b if hp.pipeline_type == "pipedream_flush" else gpipe_train_step
    )

    def init_state(key):
        params = init_swin_pipeline_params(key, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(flat_params):
        params = restack_flat_swin(flat_params, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": swin_param_specs(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": swin_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": swin_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))
    copts = cpu_sim_compiler_options(mesh)
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        lambda state, batch: loss_fn(state["params"], batch),
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)
    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=lambda sp: flatten_swin(sp, cfg, hp),
        restack_params=lambda fp: restack_flat_swin(fp, cfg, hp),
    )
