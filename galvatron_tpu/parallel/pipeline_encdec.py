"""Encoder-decoder (T5-class) pipeline: 2·pp virtual stages over the pp ring.

The reference pipelines enc-dec models by flattening encoder + decoder into
one PipeSequential and placing arbitrary layer ranges per stage
(galvatron/core/hybrid_parallel_model.py:81-153, pipeline.py:75-77), passing
the encoder output along as an extra p2p tensor. The SPMD stage stacking here
needs homogeneous layer pytrees per stack — encoder layers (self-attn + MLP)
and decoder layers (+ cross-attn) differ — so the TPU-native rendering runs
TWO COUPLED SUB-PIPELINES over the pp ring: device ``s`` holds encoder
virtual stage ``s`` and decoder virtual stage ``pp+s``, each a homogeneous
stack, and every clocked tick runs BOTH its encoder section (chunk ``t-s``)
and its decoder section (chunk ``t-pp-s``). There is no stage-diverging
control flow — GSPMD's resharding collectives span stages, so a per-stage
``lax.cond`` deadlocks (verified on the CPU sim) — and no steady-state
waste: each device does useful encoder AND decoder work every tick, so
total time ≈ (chunks + 2·pp - 1) ticks × (enc_vstage + dec_vstage), matching
the ideal interleaved schedule up to a slightly longer fill.

Ring wiring per tick:
- encoder sends ride a WRAPPED ring (device pp-1 → 0): the wrap delivers
  chunk ``t-pp``'s finished encoder output to device 0 exactly when that
  chunk's decoder starts there; device 0 applies enc_final_norm
  (token-local, SPMD-safe) to form ``ctx``;
- decoder ``(y, ctx)`` rides the plain chain (s → s+1), so every decoder
  virtual stage cross-attends against the same normed encoder output.

Backward is autodiff through the clocked scan (GPipe ordering). Encoder and
decoder sequence lengths are independent (separate carries, no padding).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import (
    LossScalerConfig,
    init_scaler_state,
    scaled_value_and_grad,
)
from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes, batch_spec
from galvatron_tpu.parallel.pipeline import cpu_sim_compiler_options
from galvatron_tpu.parallel.sharding import constrain, param_spec, sharding_tree


class EncDecLayout:
    """Per-sub-stack stage layout: ragged encoder/decoder layer counts are
    realized by PADDED stacking exactly like the decoder-only pipeline
    (pipeline.stage_layout): each sub-stack carries max(division) positions,
    stages with fewer real layers get zero-filled padding slots whose compute
    is masked out in the section functions.

    ``hp.pp_division`` of length 2*pp is read as [enc division ‖ dec
    division]; anything else (including the auto-filled single-stack default
    from HybridParallelConfig.__post_init__, which sums E+D) falls back to a
    per-stack balanced division."""

    def __init__(self, cfg: ModelConfig, hp: HybridParallelConfig):
        from galvatron_tpu.core.strategy import balanced_division

        E, D, pp = cfg.enc_layers, cfg.num_layers, hp.pp
        if E < pp or D < pp:
            raise ValueError(
                f"enc-dec pipeline needs at least pp={pp} encoder and decoder "
                f"layers (got {E} enc / {D} dec)"
            )
        div = hp.pp_division
        if div is not None and len(div) == pp:
            # HybridParallelConfig.__post_init__ auto-fills a length-pp
            # balanced division over E+D, which is meaningless for the
            # two-stack layout and ignored. Anything ELSE of length pp is
            # provably user-provided — reject it instead of silently
            # training under a different layout than the config states.
            if div != balanced_division(E + D, pp):
                raise ValueError(
                    f"enc-dec models take a 2*pp pp_division "
                    f"([enc ‖ dec] stage splits), got the single-stack "
                    f"division {div}"
                )
            div = None
        if div is not None and len(div) == 2 * pp and sum(div) == E + D:
            self.div_e, self.div_d = list(div[:pp]), list(div[pp:])
            if sum(self.div_e) != E or sum(self.div_d) != D or min(
                self.div_e + self.div_d
            ) < 1:
                raise ValueError(
                    f"enc-dec pp_division {div} must split as enc({E}) ‖ "
                    f"dec({D}) with >=1 layers per stage per stack"
                )
        else:
            self.div_e = balanced_division(E, pp)
            self.div_d = balanced_division(D, pp)
        self.off_e = list(np.cumsum([0] + self.div_e[:-1]))
        self.off_d = list(np.cumsum([0] + self.div_d[:-1]))
        self.lpe, self.lpd = max(self.div_e), max(self.div_d)
        self.pp = pp

        def positions(strats, div, off, lps, kind):
            out = []
            for q in range(lps):
                stages_with_q = [s for s in range(pp) if div[s] > q]
                ss = {strats[off[s] + q] for s in stages_with_q}
                if len(ss) > 1:
                    raise ValueError(
                        f"{kind} layers at virtual-stage position {q} must "
                        f"share one strategy across stages "
                        f"(got {sorted(map(str, ss))})"
                    )
                out.append(next(iter(ss)))
            return out

        self.enc_pos = positions(
            hp.layer_strategies[:E], self.div_e, self.off_e, self.lpe, "encoder"
        )
        self.dec_pos = positions(
            hp.layer_strategies[E:], self.div_d, self.off_d, self.lpd, "decoder"
        )


def validate_encdec_pipeline(
    cfg: ModelConfig, hp: HybridParallelConfig
) -> EncDecLayout:
    """Schedule constraints + the per-sub-stack stage layout."""
    if hp.vpp > 1:
        raise ValueError("enc-dec pipeline does not compose with vpp>1")
    if hp.chunks % hp.pp:
        raise ValueError(
            f"enc-dec pipeline needs chunks ({hp.chunks}) divisible by "
            f"pp={hp.pp} (micro-batches flow in groups of pp on the ring)"
        )
    if hp.pipeline_type != "gpipe":
        raise ValueError(
            "enc-dec pipeline implements the gpipe-ordered coupled-sub-"
            "pipeline schedule only; set pipeline_type='gpipe' "
            f"(got {hp.pipeline_type!r})"
        )
    return EncDecLayout(cfg, hp)


def _pad_stack(items, div, off, lps, pp, zeros):
    """Per-position (pp, ...) stacks from a flat per-layer list; zero padding
    where a stage has fewer real layers than the stack height."""
    return [
        jax.tree.map(
            lambda *ls: jnp.stack(ls),
            *[items[off[s] + q] if div[s] > q else zeros for s in range(pp)],
        )
        for q in range(lps)
    ]


def init_encdec_pipeline_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """embed / norms / head replicated over pp; ``enc_stages[q]`` and
    ``dec_stages[q]`` are (pp, ...) stacks — device s's slice is its virtual
    stage's q-th layer (zero-filled padding where the division is ragged)."""
    lay = validate_encdec_pipeline(cfg, hp)
    pp = hp.pp
    ks = jax.random.split(key, 6)
    base: Dict[str, Any] = {
        "embed": {
            "tok": jax.random.normal(
                ks[0], (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype
            )
            * 0.02
        },
        "enc_final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
        "final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
    }
    if cfg.pos_embed == "learned":
        pos_len = max(cfg.max_seq_len, cfg.enc_seq)
        base["embed"]["pos"] = (
            jax.random.normal(ks[1], (pos_len, cfg.hidden_size), cfg.param_dtype) * 0.02
        )
    if cfg.norm_type == "layernorm":
        base["enc_final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
        base["final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
    if not cfg.tie_word_embeddings:
        base["head"] = {
            "w": modeling._dense_init(ks[2], cfg.hidden_size, cfg.vocab_size, cfg.param_dtype)
        }
    enc_keys = jax.random.split(ks[3], cfg.enc_layers)
    dec_keys = jax.random.split(ks[4], cfg.num_layers)
    enc_layers = [modeling.init_layer_params(k, cfg) for k in enc_keys]
    dec_layers = [modeling.init_layer_params(k, cfg, cross=True) for k in dec_keys]
    base["enc_stages"] = _pad_stack(
        enc_layers, lay.div_e, lay.off_e, lay.lpe, pp,
        jax.tree.map(jnp.zeros_like, enc_layers[0]),
    )
    base["dec_stages"] = _pad_stack(
        dec_layers, lay.div_d, lay.off_d, lay.lpd, pp,
        jax.tree.map(jnp.zeros_like, dec_layers[0]),
    )
    return base


def restack_flat_encdec(flat_params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Flat ``enc_layers``/``layers`` lists → the enc/dec virtual-stage
    stacks (portable-checkpoint layout); zero padding on ragged divisions."""
    lay = validate_encdec_pipeline(cfg, hp)
    params = {
        k: v for k, v in flat_params.items() if k not in ("enc_layers", "layers")
    }
    enc = flat_params["enc_layers"]
    dec = flat_params["layers"]
    params["enc_stages"] = _pad_stack(
        enc, lay.div_e, lay.off_e, lay.lpe, hp.pp,
        jax.tree.map(jnp.zeros_like, enc[0]),
    )
    params["dec_stages"] = _pad_stack(
        dec, lay.div_d, lay.off_d, lay.lpd, hp.pp,
        jax.tree.map(jnp.zeros_like, dec[0]),
    )
    return params


def flatten_encdec(params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Inverse of restack_flat_encdec (padded slots dropped)."""
    lay = validate_encdec_pipeline(cfg, hp)
    flat = {
        k: v for k, v in params.items() if k not in ("enc_stages", "dec_stages")
    }

    def unstack(stacks, div, off, total):
        out = [None] * total
        for s in range(hp.pp):
            for q in range(div[s]):
                out[off[s] + q] = jax.tree.map(lambda a, s_=s: a[s_], stacks[q])
        return out

    flat["enc_layers"] = unstack(params["enc_stages"], lay.div_e, lay.off_e, cfg.enc_layers)
    flat["layers"] = unstack(params["dec_stages"], lay.div_d, lay.off_d, cfg.num_layers)
    return flat


def encdec_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    lay = validate_encdec_pipeline(cfg, hp)
    enc_pos, dec_pos = lay.enc_pos, lay.dec_pos
    embed_strategy = LayerStrategy(
        tp=hp.vocab_tp, tp_consec=True, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    is_leaf = lambda x: hasattr(x, "shape")
    model_annots = {
        "embed": {"tok": ("tp", "fsdp")},
        "enc_final_norm": {"scale": ("fsdp",)},
        "final_norm": {"scale": ("fsdp",)},
    }
    if cfg.pos_embed == "learned":
        model_annots["embed"]["pos"] = ("fsdp", None)
    if cfg.norm_type == "layernorm":
        model_annots["enc_final_norm"]["bias"] = ("fsdp",)
        model_annots["final_norm"]["bias"] = ("fsdp",)
    if not cfg.tie_word_embeddings:
        model_annots["head"] = {"w": ("fsdp", "tp")}

    def stack_specs(shapes, annots, pos_strategies):
        return [
            jax.tree.map(
                lambda leaf, a: P(
                    "pp",
                    *param_spec(
                        leaf.shape[1:], a, axes, pos_strategies[q],
                        for_opt_state=for_opt_state,
                    ),
                ),
                shapes[q],
                annots,
                is_leaf=is_leaf,
            )
            for q in range(len(shapes))
        ]

    specs: Dict[str, Any] = {}
    for key in params_shape:
        if key == "enc_stages":
            specs[key] = stack_specs(
                params_shape[key], modeling.layer_annotations(cfg), enc_pos
            )
        elif key == "dec_stages":
            specs[key] = stack_specs(
                params_shape[key], modeling.layer_annotations(cfg, cross=True), dec_pos
            )
        else:
            specs[key] = jax.tree.map(
                lambda leaf, a: param_spec(
                    leaf.shape, a, axes, embed_strategy, for_opt_state=for_opt_state
                ),
                params_shape[key],
                model_annots[key],
                is_leaf=is_leaf,
            )
    return specs


def _make_section_fns(cfg: ModelConfig, hp: HybridParallelConfig, mesh, axes):
    """(enc_section, dec_section): run one virtual stage's layers with
    per-position sharding constraints + remat. Ragged divisions mask padding
    positions to identity (runs inside the manual-'pp' shard_map, so the
    stage index comes from lax.axis_index)."""
    lay = validate_encdec_pipeline(cfg, hp)
    enc_pos, dec_pos = lay.enc_pos, lay.dec_pos
    uneven_e = len(set(lay.div_e)) > 1
    uneven_d = len(set(lay.div_d)) > 1

    def act_spec(s: LayerStrategy) -> P:
        bs = batch_spec(axes, s)
        return P(bs[0], bs[1], None)

    cos_e = modeling.rope_tables(cfg, cfg.enc_seq) if cfg.pos_embed == "rope" else None

    def enc_section(stage_params, x):
        n_active = (
            jnp.asarray(lay.div_e)[jax.lax.axis_index("pp")] if uneven_e else None
        )
        for q, s in enumerate(enc_pos):
            x = constrain(x, mesh, act_spec(s))
            run = lambda x_, lp_: modeling.encoder_layer(
                x_, lp_, cfg, cos_e, remat_attn=(s.ckpt == "selective")
            )
            if s.ckpt == "full":
                run = jax.checkpoint(run)
            out = run(x, stage_params[q])
            x = out if n_active is None else jnp.where(q < n_active, out, x)
        return x

    def dec_section(stage_params, x, ctx):
        cos_d = (
            modeling.rope_tables(cfg, x.shape[1]) if cfg.pos_embed == "rope" else None
        )
        n_active = (
            jnp.asarray(lay.div_d)[jax.lax.axis_index("pp")] if uneven_d else None
        )
        for q, s in enumerate(dec_pos):
            x = constrain(x, mesh, act_spec(s))
            run = lambda x_, lp_: modeling.decoder_layer(
                x_, lp_, cfg, cos_d, None,
                remat_attn=(s.ckpt == "selective"), enc_out=ctx,
            )
            if s.ckpt == "full":
                run = jax.checkpoint(run)
            out = run(x, stage_params[q])
            x = out if n_active is None else jnp.where(q < n_active, out, x)
        return x

    return enc_section, dec_section


def build_encdec_pipeline_runtime(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam: AdamConfig,
    global_batch_size: int,
    seq_len: int,
):
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime

    pp, chunks = hp.pp, max(1, hp.chunks)
    if global_batch_size % chunks:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks
    validate_encdec_pipeline(cfg, hp)
    enc_section, dec_section = _make_section_fns(cfg, hp, mesh, axes)

    S_e = cfg.enc_seq
    S_d = cfg.sample_len - cfg.enc_seq  # decoder input length (dec[:, :-1])
    # two coupled sub-pipelines advancing in lockstep each tick: every device
    # runs its ENCODER section on chunk t-s and its DECODER section on chunk
    # t-pp-s. The encoder send rides a wrapped ring (device pp-1's finished
    # encoder output reaches device 0 exactly when that chunk's decoder
    # starts there); decoder (y, ctx) rides the plain chain. Every device
    # does real work on both sections every steady-state tick — no stage-
    # diverging control flow (GSPMD resharding collectives span stages, so a
    # per-stage lax.cond deadlocks; verified on the CPU sim), no 2x waste.
    ring_wrap = [(i, (i + 1) % pp) for i in range(pp)]
    chain = [(i, i + 1) for i in range(pp - 1)]
    # last useful write: chunk chunks-1's decoder at device pp-1, tick
    # (chunks-1) + pp + (pp-1) = chunks + 2pp - 2 -> T = chunks + 2pp - 1
    T = chunks + 2 * pp - 1
    full_spec = P(("pp",) + axes.data_axes, None, None)

    def pipeline(enc_stages, dec_stages, enc_norm, enc_mbs, dec_mbs):
        """Manual-'pp' shard_map body. enc_mbs (chunks, mb, S_e, H) and
        dec_mbs (chunks, mb, S_d, H) are replicated; returns (1, chunks, mb,
        S_d, H) — real decoder outputs in the pp-1 slice."""
        enc_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), enc_stages)
        dec_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), dec_stages)
        s = jax.lax.axis_index("pp")
        h = cfg.hidden_size
        carry0 = {
            "enc": jnp.zeros((mb, S_e, h), enc_mbs.dtype),
            "dec": jnp.zeros((mb, S_d, h), enc_mbs.dtype),
            "ctx": jnp.zeros((mb, S_e, h), enc_mbs.dtype),
            "ys": jnp.zeros((chunks + 1, mb, S_d, h), enc_mbs.dtype),
        }

        def tick(carry, t):
            recv_e = jax.lax.ppermute(carry["enc"], "pp", ring_wrap)
            recv_d = jax.lax.ppermute(carry["dec"], "pp", chain)
            recv_ctx = jax.lax.ppermute(carry["ctx"], "pp", chain)

            m_e = jnp.clip(t - s, 0, chunks - 1)
            m_d_raw = t - pp - s
            m_d = jnp.clip(m_d_raw, 0, chunks - 1)
            enc_emb = jax.lax.dynamic_index_in_dim(enc_mbs, m_e, keepdims=False)
            dec_emb = jax.lax.dynamic_index_in_dim(dec_mbs, m_d, keepdims=False)

            # encoder sub-pipeline
            x_in = jnp.where(s == 0, enc_emb, recv_e)
            enc_out = enc_section(enc_stages, x_in)

            # decoder sub-pipeline: device 0 enters the chunk whose encoder
            # output just wrapped around (recv_e is chunk t-pp's enc_out
            # there); enc_final_norm is token-local — SPMD-safe
            y_in = jnp.where(s == 0, dec_emb, recv_d)
            ctx_in = jnp.where(
                s == 0, modeling.norm(recv_e, enc_norm, cfg), recv_ctx
            )
            y_out = dec_section(dec_stages, y_in, ctx_in)

            # device pp-1 holds the finished decoder outputs (gpipe-style)
            valid = (m_d_raw >= 0) & (m_d_raw < chunks)
            slot = jnp.where(valid, m_d, chunks)
            ys = jax.lax.dynamic_update_index_in_dim(carry["ys"], y_out, slot, 0)
            return {"enc": enc_out, "dec": y_out, "ctx": ctx_in, "ys": ys}, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        return carry["ys"][None, :chunks]

    pipe_sm = jax.shard_map(
        pipeline,
        mesh=mesh,
        in_specs=(P("pp"), P("pp"), P(), P(), P()),
        out_specs=P("pp"),
        axis_names={"pp"},
        check_vma=False,
    )

    def loss_fn(params, batch):
        enc_tokens = batch[:, :S_e]
        dec = batch[:, S_e:]
        dec_tokens, labels = dec[:, :-1], dec[:, 1:]
        xe = modeling.embed(enc_tokens, params, cfg)
        xd = modeling.embed(dec_tokens, params, cfg)
        xe = constrain(xe, mesh, full_spec)
        xd = constrain(xd, mesh, full_spec)
        enc_mbs = xe.reshape(chunks, mb, S_e, cfg.hidden_size)
        dec_mbs = xd.reshape(chunks, mb, S_d, cfg.hidden_size)
        ys = pipe_sm(
            params["enc_stages"], params["dec_stages"], params["enc_final_norm"],
            enc_mbs, dec_mbs,
        )  # (pp, chunks, mb, S_d, H); real outputs in the pp-1 slice
        y = ys[-1].reshape(global_batch_size, S_d, cfg.hidden_size)
        y = constrain(y, mesh, full_spec)
        y = modeling.norm(y, params["final_norm"], cfg)
        logits = modeling.lm_head(y, params, cfg)
        ssum, n = modeling.cross_entropy_sum(logits, labels)
        return ssum / jnp.maximum(n, 1)

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def train_step(state, batch):
        if fp16:
            loss, grads = scaled_value_and_grad(loss_fn, state["scaler"]["scale"])(
                state["params"], batch
            )
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    def init_state(key):
        params = init_encdec_pipeline_params(key, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(flat_params):
        params = restack_flat_encdec(flat_params, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": encdec_param_specs(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": encdec_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": encdec_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))
    copts = cpu_sim_compiler_options()
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        lambda state, batch: loss_fn(state["params"], batch),
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)
    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=lambda sp: flatten_encdec(sp, cfg, hp),
        restack_params=lambda fp: restack_flat_encdec(fp, cfg, hp),
    )
