"""Encoder-decoder (T5-class) pipeline: 2·pp virtual stages over the pp ring.

The reference pipelines enc-dec models by flattening encoder + decoder into
one PipeSequential and placing arbitrary layer ranges per stage
(galvatron/core/hybrid_parallel_model.py:81-153, pipeline.py:75-77), passing
the encoder output along as an extra p2p tensor. The SPMD stage stacking here
needs homogeneous layer pytrees per stack — encoder layers (self-attn + MLP)
and decoder layers (+ cross-attn) differ — so the TPU-native rendering runs
TWO COUPLED SUB-PIPELINES over the pp ring: device ``s`` holds encoder
virtual stage ``s`` and decoder virtual stage ``pp+s``, each a homogeneous
stack, and every clocked tick runs BOTH its encoder section (chunk ``t-s``)
and its decoder section (chunk ``t-pp-s``). There is no stage-diverging
control flow — GSPMD's resharding collectives span stages, so a per-stage
``lax.cond`` deadlocks (verified on the CPU sim) — and no steady-state
waste: each device does useful encoder AND decoder work every tick, so
total time ≈ (chunks + 2·pp - 1) ticks × (enc_vstage + dec_vstage), matching
the ideal interleaved schedule up to a slightly longer fill.

Ring wiring per tick:
- encoder sends ride a WRAPPED ring (device pp-1 → 0): the wrap delivers
  chunk ``t-pp``'s finished encoder output to device 0 exactly when that
  chunk's decoder starts there; device 0 applies enc_final_norm
  (token-local, SPMD-safe) to form ``ctx``;
- decoder ``(y, ctx)`` rides the plain chain (s → s+1), so every decoder
  virtual stage cross-attends against the same normed encoder output.

Backward is autodiff through the clocked scan (GPipe ordering). Encoder and
decoder sequence lengths are independent (separate carries, no padding).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import (
    LossScalerConfig,
    init_scaler_state,
    scaled_value_and_grad,
)
from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import MeshAxes, batch_spec
from galvatron_tpu.parallel.pipeline import cpu_sim_compiler_options
from galvatron_tpu.parallel.sharding import (
    constrain,
    param_spec,
    sharding_tree,
    with_flash_shard_ctx,
    with_tp_overlap_ctx,
)


class EncDecLayout:
    """Per-sub-stack stage layout: ragged encoder/decoder layer counts are
    realized by PADDED stacking exactly like the decoder-only pipeline
    (pipeline.stage_layout): each sub-stack carries max(division) positions,
    stages with fewer real layers get zero-filled padding slots whose compute
    is masked out in the section functions.

    ``hp.pp_division`` of length 2*pp is read as [enc division ‖ dec
    division]; anything else (including the auto-filled single-stack default
    from HybridParallelConfig.__post_init__, which sums E+D) falls back to a
    per-stack balanced division."""

    def __init__(self, cfg: ModelConfig, hp: HybridParallelConfig):
        from galvatron_tpu.core.strategy import balanced_division

        E, D, pp = cfg.enc_layers, cfg.num_layers, hp.pp
        if E < 1 or D < 1:
            raise ValueError(
                f"enc-dec pipeline needs at least one encoder and one decoder "
                f"layer (got {E} enc / {D} dec)"
            )
        # a sub-stack SMALLER than pp is fine: balanced_division yields zero
        # entries for the tail stages, whose padded positions are fully
        # masked (identity sections that just forward the ring traffic) —
        # the reference places arbitrary layer ranges per stage the same way
        # (galvatron/core/pipeline/pipeline.py:75-77)
        div = hp.pp_division
        if div is not None and len(div) == pp:
            # HybridParallelConfig.__post_init__ auto-fills a length-pp
            # balanced division over E+D, which is meaningless for the
            # two-stack layout and ignored. Anything ELSE of length pp is
            # provably user-provided — reject it instead of silently
            # training under a different layout than the config states.
            if div != balanced_division(E + D, pp):
                raise ValueError(
                    f"enc-dec models take a 2*pp pp_division "
                    f"([enc ‖ dec] stage splits), got the single-stack "
                    f"division {div}"
                )
            div = None
        if div is not None and len(div) == 2 * pp and sum(div) == E + D:
            self.div_e, self.div_d = list(div[:pp]), list(div[pp:])
            if sum(self.div_e) != E or sum(self.div_d) != D or min(
                self.div_e + self.div_d
            ) < 0:
                raise ValueError(
                    f"enc-dec pp_division {div} must split as enc({E}) ‖ "
                    f"dec({D}) with non-negative per-stage counts"
                )
        else:
            self.div_e = balanced_division(E, pp)
            self.div_d = balanced_division(D, pp)
        self.off_e = list(np.cumsum([0] + self.div_e[:-1]))
        self.off_d = list(np.cumsum([0] + self.div_d[:-1]))
        self.lpe, self.lpd = max(self.div_e), max(self.div_d)
        self.pp = pp
        from galvatron_tpu.parallel.pipeline import position_strategies

        self.enc_pos = position_strategies(
            hp.layer_strategies[:E], self.div_e, self.off_e, "encoder"
        )
        self.dec_pos = position_strategies(
            hp.layer_strategies[E:], self.div_d, self.off_d, "decoder"
        )


def validate_encdec_pipeline(
    cfg: ModelConfig, hp: HybridParallelConfig
) -> EncDecLayout:
    """Schedule constraints + the per-sub-stack stage layout."""
    if hp.vpp > 1:
        raise ValueError("enc-dec pipeline does not compose with vpp>1")
    if hp.pipeline_type not in ("gpipe", "pipedream_flush"):
        raise ValueError(
            f"unknown pipeline_type {hp.pipeline_type!r} for the enc-dec "
            "pipeline (gpipe | pipedream_flush)"
        )
    return EncDecLayout(cfg, hp)


def _pad_stack(items, div, off, lps, pp, zeros):
    """Per-position (pp, ...) stacks from a flat per-layer list; zero padding
    where a stage has fewer real layers than the stack height."""
    return [
        jax.tree.map(
            lambda *ls: jnp.stack(ls),
            *[items[off[s] + q] if div[s] > q else zeros for s in range(pp)],
        )
        for q in range(lps)
    ]


def init_encdec_pipeline_params(key, cfg: ModelConfig, hp: HybridParallelConfig):
    """embed / norms / head replicated over pp; ``enc_stages[q]`` and
    ``dec_stages[q]`` are (pp, ...) stacks — device s's slice is its virtual
    stage's q-th layer (zero-filled padding where the division is ragged)."""
    lay = validate_encdec_pipeline(cfg, hp)
    pp = hp.pp
    ks = jax.random.split(key, 6)
    base: Dict[str, Any] = {
        "embed": {
            "tok": jax.random.normal(
                ks[0], (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype
            )
            * 0.02
        },
        "enc_final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
        "final_norm": {"scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype)},
    }
    if cfg.pos_embed == "learned":
        pos_len = max(cfg.max_seq_len, cfg.enc_seq)
        base["embed"]["pos"] = (
            jax.random.normal(ks[1], (pos_len, cfg.hidden_size), cfg.param_dtype) * 0.02
        )
    if cfg.norm_type == "layernorm":
        base["enc_final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
        base["final_norm"]["bias"] = jnp.zeros((cfg.hidden_size,), cfg.param_dtype)
    if not cfg.tie_word_embeddings:
        base["head"] = {
            "w": modeling._dense_init(ks[2], cfg.hidden_size, cfg.vocab_size, cfg.param_dtype)
        }
    enc_keys = jax.random.split(ks[3], cfg.enc_layers)
    dec_keys = jax.random.split(ks[4], cfg.num_layers)
    enc_layers = [modeling.init_layer_params(k, cfg) for k in enc_keys]
    dec_layers = [modeling.init_layer_params(k, cfg, cross=True) for k in dec_keys]
    base["enc_stages"] = _pad_stack(
        enc_layers, lay.div_e, lay.off_e, lay.lpe, pp,
        jax.tree.map(jnp.zeros_like, enc_layers[0]),
    )
    base["dec_stages"] = _pad_stack(
        dec_layers, lay.div_d, lay.off_d, lay.lpd, pp,
        jax.tree.map(jnp.zeros_like, dec_layers[0]),
    )
    return base


def restack_flat_encdec(flat_params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Flat ``enc_layers``/``layers`` lists → the enc/dec virtual-stage
    stacks (portable-checkpoint layout); zero padding on ragged divisions."""
    lay = validate_encdec_pipeline(cfg, hp)
    params = {
        k: v for k, v in flat_params.items() if k not in ("enc_layers", "layers")
    }
    enc = flat_params["enc_layers"]
    dec = flat_params["layers"]
    params["enc_stages"] = _pad_stack(
        enc, lay.div_e, lay.off_e, lay.lpe, hp.pp,
        jax.tree.map(jnp.zeros_like, enc[0]),
    )
    params["dec_stages"] = _pad_stack(
        dec, lay.div_d, lay.off_d, lay.lpd, hp.pp,
        jax.tree.map(jnp.zeros_like, dec[0]),
    )
    return params


def flatten_encdec(params, cfg: ModelConfig, hp: HybridParallelConfig):
    """Inverse of restack_flat_encdec (padded slots dropped)."""
    lay = validate_encdec_pipeline(cfg, hp)
    flat = {
        k: v for k, v in params.items() if k not in ("enc_stages", "dec_stages")
    }

    def unstack(stacks, div, off, total):
        out = [None] * total
        for s in range(hp.pp):
            for q in range(div[s]):
                out[off[s] + q] = jax.tree.map(lambda a, s_=s: a[s_], stacks[q])
        return out

    flat["enc_layers"] = unstack(params["enc_stages"], lay.div_e, lay.off_e, cfg.enc_layers)
    flat["layers"] = unstack(params["dec_stages"], lay.div_d, lay.off_d, cfg.num_layers)
    return flat


def encdec_param_specs(
    params_shape, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
):
    lay = validate_encdec_pipeline(cfg, hp)
    enc_pos, dec_pos = lay.enc_pos, lay.dec_pos
    embed_strategy = LayerStrategy(
        tp=hp.vocab_tp, tp_consec=True, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    is_leaf = lambda x: hasattr(x, "shape")
    model_annots = {
        "embed": {"tok": ("tp", "fsdp")},
        "enc_final_norm": {"scale": ("fsdp",)},
        "final_norm": {"scale": ("fsdp",)},
    }
    if cfg.pos_embed == "learned":
        model_annots["embed"]["pos"] = ("fsdp", None)
    if cfg.norm_type == "layernorm":
        model_annots["enc_final_norm"]["bias"] = ("fsdp",)
        model_annots["final_norm"]["bias"] = ("fsdp",)
    if not cfg.tie_word_embeddings:
        model_annots["head"] = {"w": ("fsdp", "tp")}

    def stack_specs(shapes, annots, pos_strategies):
        return [
            jax.tree.map(
                lambda leaf, a: P(
                    "pp",
                    *param_spec(
                        leaf.shape[1:], a, axes, pos_strategies[q],
                        for_opt_state=for_opt_state,
                    ),
                ),
                shapes[q],
                annots,
                is_leaf=is_leaf,
            )
            for q in range(len(shapes))
        ]

    specs: Dict[str, Any] = {}
    for key in params_shape:
        if key == "enc_stages":
            specs[key] = stack_specs(
                params_shape[key], modeling.layer_annotations(cfg), enc_pos
            )
        elif key == "dec_stages":
            specs[key] = stack_specs(
                params_shape[key], modeling.layer_annotations(cfg, cross=True), dec_pos
            )
        else:
            specs[key] = jax.tree.map(
                lambda leaf, a: param_spec(
                    leaf.shape, a, axes, embed_strategy, for_opt_state=for_opt_state
                ),
                params_shape[key],
                model_annots[key],
                is_leaf=is_leaf,
            )
    return specs


def _make_section_fns(cfg: ModelConfig, hp: HybridParallelConfig, mesh, axes):
    """(enc_section, dec_section): run one virtual stage's layers with
    per-position sharding constraints + remat. Ragged divisions mask padding
    positions to identity (runs inside the manual-'pp' shard_map, so the
    stage index comes from lax.axis_index)."""
    lay = validate_encdec_pipeline(cfg, hp)
    enc_pos, dec_pos = lay.enc_pos, lay.dec_pos
    uneven_e = len(set(lay.div_e)) > 1
    uneven_d = len(set(lay.div_d)) > 1

    def act_spec(s: LayerStrategy) -> P:
        bs = batch_spec(axes, s)
        return P(bs[0], bs[1], None)

    cos_e = modeling.rope_tables(cfg, cfg.enc_seq) if cfg.pos_embed == "rope" else None

    def enc_section(stage_params, x):
        n_active = (
            jnp.asarray(lay.div_e)[jax.lax.axis_index("pp")] if uneven_e else None
        )
        for q, s in enumerate(enc_pos):
            x = constrain(x, mesh, act_spec(s))
            lcfg = with_flash_shard_ctx(cfg, s, mesh, axes)
            lcfg = with_tp_overlap_ctx(lcfg, s, mesh, axes)
            if s.ckpt == "full" and lcfg.mlp_recompute != "off":
                # full-layer remat subsumes the gate-save policy
                lcfg = lcfg.replace(mlp_recompute="off")
            run = lambda x_, lp_, lcfg=lcfg: modeling.encoder_layer(
                x_, lp_, lcfg, cos_e, remat_attn=(s.ckpt == "selective")
            )
            if s.ckpt == "full":
                run = jax.checkpoint(run)
            out = run(x, stage_params[q])
            x = out if n_active is None else jnp.where(q < n_active, out, x)
        return x

    def dec_section(stage_params, x, ctx):
        cos_d = (
            modeling.rope_tables(cfg, x.shape[1]) if cfg.pos_embed == "rope" else None
        )
        n_active = (
            jnp.asarray(lay.div_d)[jax.lax.axis_index("pp")] if uneven_d else None
        )
        for q, s in enumerate(dec_pos):
            x = constrain(x, mesh, act_spec(s))
            lcfg = with_flash_shard_ctx(cfg, s, mesh, axes)
            lcfg = with_tp_overlap_ctx(lcfg, s, mesh, axes)
            if s.ckpt == "full" and lcfg.mlp_recompute != "off":
                lcfg = lcfg.replace(mlp_recompute="off")
            run = lambda x_, lp_, lcfg=lcfg: modeling.decoder_layer(
                x_, lp_, lcfg, cos_d, None,
                remat_attn=(s.ckpt == "selective"), enc_out=ctx,
            )
            if s.ckpt == "full":
                run = jax.checkpoint(run)
            out = run(x, stage_params[q])
            x = out if n_active is None else jnp.where(q < n_active, out, x)
        return x

    return enc_section, dec_section


def build_encdec_pipeline_runtime(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Mesh,
    axes: MeshAxes,
    adam: AdamConfig,
    global_batch_size: int,
    seq_len: int,
):
    from galvatron_tpu.parallel.hybrid import HybridParallelRuntime

    pp, chunks = hp.pp, max(1, hp.chunks)
    if global_batch_size % chunks:
        raise ValueError(f"global batch {global_batch_size} not divisible by chunks {chunks}")
    mb = global_batch_size // chunks
    validate_encdec_pipeline(cfg, hp)
    enc_section, dec_section = _make_section_fns(cfg, hp, mesh, axes)

    S_e = cfg.enc_seq
    S_d = cfg.sample_len - cfg.enc_seq  # decoder input length (dec[:, :-1])
    # two coupled sub-pipelines advancing in lockstep each tick: every device
    # runs its ENCODER section on chunk t-s and its DECODER section on chunk
    # t-pp-s. The encoder send rides a wrapped ring (device pp-1's finished
    # encoder output reaches device 0 exactly when that chunk's decoder
    # starts there); decoder (y, ctx) rides the plain chain. Every device
    # does real work on both sections every steady-state tick — no stage-
    # diverging control flow (GSPMD resharding collectives span stages, so a
    # per-stage lax.cond deadlocks; verified on the CPU sim), no 2x waste.
    ring_wrap = [(i, (i + 1) % pp) for i in range(pp)]
    chain = [(i, i + 1) for i in range(pp - 1)]
    # last useful write: chunk chunks-1's decoder at device pp-1, tick
    # (chunks-1) + pp + (pp-1) = chunks + 2pp - 2 -> T = chunks + 2pp - 1
    T = chunks + 2 * pp - 1
    full_spec = P(("pp",) + axes.data_axes, None, None)

    def pipeline(enc_stages, dec_stages, enc_norm, enc_mbs, dec_mbs):
        """Manual-'pp' shard_map body. enc_mbs (chunks, mb, S_e, H) and
        dec_mbs (chunks, mb, S_d, H) are replicated; returns (1, chunks, mb,
        S_d, H) — real decoder outputs in the pp-1 slice."""
        enc_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), enc_stages)
        dec_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), dec_stages)
        s = jax.lax.axis_index("pp")
        h = cfg.hidden_size
        carry0 = {
            "enc": jnp.zeros((mb, S_e, h), enc_mbs.dtype),
            "dec": jnp.zeros((mb, S_d, h), enc_mbs.dtype),
            "ctx": jnp.zeros((mb, S_e, h), enc_mbs.dtype),
            "ys": jnp.zeros((chunks + 1, mb, S_d, h), enc_mbs.dtype),
        }

        def tick(carry, t):
            recv_e = jax.lax.ppermute(carry["enc"], "pp", ring_wrap)
            recv_d = jax.lax.ppermute(carry["dec"], "pp", chain)
            recv_ctx = jax.lax.ppermute(carry["ctx"], "pp", chain)

            m_e = jnp.clip(t - s, 0, chunks - 1)
            m_d_raw = t - pp - s
            m_d = jnp.clip(m_d_raw, 0, chunks - 1)
            enc_emb = jax.lax.dynamic_index_in_dim(enc_mbs, m_e, keepdims=False)
            dec_emb = jax.lax.dynamic_index_in_dim(dec_mbs, m_d, keepdims=False)

            # encoder sub-pipeline
            x_in = jnp.where(s == 0, enc_emb, recv_e)
            enc_out = enc_section(enc_stages, x_in)

            # decoder sub-pipeline: device 0 enters the chunk whose encoder
            # output just wrapped around (recv_e is chunk t-pp's enc_out
            # there); enc_final_norm is token-local — SPMD-safe
            y_in = jnp.where(s == 0, dec_emb, recv_d)
            ctx_in = jnp.where(
                s == 0, modeling.norm(recv_e, enc_norm, cfg), recv_ctx
            )
            y_out = dec_section(dec_stages, y_in, ctx_in)

            # device pp-1 holds the finished decoder outputs (gpipe-style)
            valid = (m_d_raw >= 0) & (m_d_raw < chunks)
            slot = jnp.where(valid, m_d, chunks)
            ys = jax.lax.dynamic_update_index_in_dim(carry["ys"], y_out, slot, 0)
            return {"enc": enc_out, "dec": y_out, "ctx": ctx_in, "ys": ys}, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        return carry["ys"][None, :chunks]

    pipe_sm = compat.shard_map(
        pipeline,
        mesh=mesh,
        in_specs=(P("pp"), P("pp"), P(), P(), P()),
        out_specs=P("pp"),
        axis_names={"pp"},
        check_vma=False,
    )

    def loss_fn(params, batch):
        enc_tokens = batch[:, :S_e]
        dec = batch[:, S_e:]
        dec_tokens, labels = dec[:, :-1], dec[:, 1:]
        xe = modeling.embed(enc_tokens, params, cfg)
        xd = modeling.embed(dec_tokens, params, cfg)
        xe = constrain(xe, mesh, full_spec)
        xd = constrain(xd, mesh, full_spec)
        enc_mbs = xe.reshape(chunks, mb, S_e, cfg.hidden_size)
        dec_mbs = xd.reshape(chunks, mb, S_d, cfg.hidden_size)
        ys = pipe_sm(
            params["enc_stages"], params["dec_stages"], params["enc_final_norm"],
            enc_mbs, dec_mbs,
        )  # (pp, chunks, mb, S_d, H); real outputs in the pp-1 slice
        y = ys[-1].reshape(global_batch_size, S_d, cfg.hidden_size)
        y = constrain(y, mesh, full_spec)
        y = modeling.norm(y, params["final_norm"], cfg)
        logits = modeling.lm_head(y, params, cfg)
        ssum, n = modeling.cross_entropy_sum(logits, labels, remat=modeling.ce_remat(cfg))
        return ssum / jnp.maximum(n, 1)

    fp16 = hp.mixed_precision == "fp16"
    scaler_cfg = LossScalerConfig()

    def gpipe_train_step(state, batch):
        if fp16:
            loss, grads = scaled_value_and_grad(loss_fn, state["scaler"]["scale"])(
                state["params"], batch
            )
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    # ------------------------------------------------------------------
    # 1F1B (pipedream_flush) ordering: hand-written backward over the coupled
    # sub-pipelines. The coupled pipeline is an interleaved virtual pipeline
    # of depth 2*pp (enc virtual stage s and dec virtual stage pp+s live on
    # device s), so the backward mirrors pipeline_1f1b: the dec backward wave
    # starts at the last device in the SAME tick as that chunk's dec forward,
    # rides the down-chain accumulating the cross-attention context cotangent,
    # wraps at device 0 to seed the enc backward wave. Backward recomputes
    # each section from stashed inputs (ring buffers bounded by the schedule
    # depth, independent of chunks — the 1F1B property the gpipe-ordered
    # autodiff backward lacks). enc_final_norm is folded INTO the dec section
    # here (ctx rides the chain pre-norm), so its vjp and parameter grads fall
    # out of the per-stage dec vjp with no separate norm bookkeeping.
    #
    #   enc fwd: m = t - s            dec fwd: m = t - pp - s
    #   dec bwd: m = t - (3pp-2) + s  enc bwd: m = t - (4pp-2) + s
    #   T = chunks + 4pp - 2;  stashes: enc min(chunks, 4pp-1),
    #   dec/ctx min(chunks, 2pp-1)   (+1 sacrificial slot each)
    # ------------------------------------------------------------------
    from galvatron_tpu.parallel.pipeline_1f1b import _head_loss

    head_keys = ("final_norm", "embed") if cfg.tie_word_embeddings else ("final_norm", "head")
    n_se = min(chunks, 4 * pp - 1)
    n_sd = min(chunks, 2 * pp - 1)
    T_1f1b = chunks + 4 * pp - 2
    n_static = mb * S_d  # loss-carrying positions per micro-batch
    chain_down = [(i + 1, i) for i in range(pp - 1)]
    ring_wrap_down = [(i, (i - 1) % pp) for i in range(pp)]

    def dec_sec_norm(dec_stages_, enc_norm_, y, pre_ctx):
        return dec_section(dec_stages_, y, modeling.norm(pre_ctx, enc_norm_, cfg))

    def pipeline_body_1f1b(enc_stages, dec_stages, enc_norm, head_sub,
                           enc_mbs, dec_mbs, labels_mbs, scale):
        enc_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), enc_stages)
        dec_stages = jax.tree.map(lambda a: jnp.squeeze(a, 0), dec_stages)
        s = jax.lax.axis_index("pp")
        is_last = s == pp - 1
        is_first = s == 0
        h = cfg.hidden_size
        dt = enc_mbs.dtype
        ea = (mb, S_e, h)
        da = (mb, S_d, h)
        f32 = lambda tree: jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), tree)

        carry0 = {
            "fe": jnp.zeros(ea, dt),       # enc fwd send (wrapped up-ring)
            "fd": jnp.zeros(da, dt),       # dec fwd send (up chain)
            "fctx": jnp.zeros(ea, dt),     # pre-norm ctx send (up chain)
            "bdy": jnp.zeros(da, dt),      # dec bwd dy send (down chain)
            "bdctx": jnp.zeros(ea, jnp.float32),  # accumulated dctx (down chain)
            "be": jnp.zeros(ea, jnp.float32),     # enc bwd seed (wrapped down-ring)
            "bey": jnp.zeros(ea, dt),      # enc bwd dy send (down chain)
            "stash_e": jnp.zeros((n_se + 1,) + ea, dt),
            "stash_d": jnp.zeros((n_sd + 1,) + da, dt),
            "stash_ctx": jnp.zeros((n_sd + 1,) + ea, dt),
            "dw_e": f32(enc_stages),
            "dw_d": f32(dec_stages),
            "dnorm": f32(enc_norm),
            "dhead": f32(head_sub),
            "dxe": jnp.zeros((chunks + 1,) + ea, jnp.float32),
            "dxd": jnp.zeros((chunks + 1,) + da, jnp.float32),
            "loss_sum": jnp.zeros((), jnp.float32),
            "tok": jnp.zeros((), jnp.float32),
        }

        def tick(carry, t):
            re = jax.lax.ppermute(carry["fe"], "pp", ring_wrap)
            rd = jax.lax.ppermute(carry["fd"], "pp", chain)
            rctx = jax.lax.ppermute(carry["fctx"], "pp", chain)
            rdy_d = jax.lax.ppermute(carry["bdy"], "pp", chain_down)
            rdctx = jax.lax.ppermute(carry["bdctx"], "pp", chain_down)
            rbe = jax.lax.ppermute(carry["be"], "pp", ring_wrap_down)
            rdy_e = jax.lax.ppermute(carry["bey"], "pp", chain_down)

            # ---- encoder forward
            m_ef = t - s
            ef_valid = (m_ef >= 0) & (m_ef < chunks)
            mef_c = jnp.clip(m_ef, 0, chunks - 1)
            x_in_e = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(enc_mbs, mef_c, keepdims=False), re
            )
            out_e = enc_section(enc_stages, x_in_e)
            e_slot = jnp.where(ef_valid, jnp.mod(mef_c, n_se), n_se)
            stash_e = jax.lax.dynamic_update_index_in_dim(
                carry["stash_e"], x_in_e, e_slot, 0
            )

            # ---- decoder forward (ctx rides the chain PRE-norm; device 0's
            # ctx is the wrapped enc output of the same chunk)
            m_df = t - pp - s
            df_valid = (m_df >= 0) & (m_df < chunks)
            mdf_c = jnp.clip(m_df, 0, chunks - 1)
            y_in = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(dec_mbs, mdf_c, keepdims=False), rd
            )
            ctx_in = jnp.where(is_first, re, rctx)
            out_d = dec_sec_norm(dec_stages, enc_norm, y_in, ctx_in)
            d_slot = jnp.where(df_valid, jnp.mod(mdf_c, n_sd), n_sd)
            stash_d = jax.lax.dynamic_update_index_in_dim(carry["stash_d"], y_in, d_slot, 0)
            stash_ctx = jax.lax.dynamic_update_index_in_dim(
                carry["stash_ctx"], ctx_in, d_slot, 0
            )

            # ---- decoder backward (recompute from stash; head loss on the
            # recomputed output at the last device, 1F1B same-tick fwd/bwd)
            m_db = t - (3 * pp - 2) + s
            db_valid = (m_db >= 0) & (m_db < chunks)
            mdb_c = jnp.clip(m_db, 0, chunks - 1)
            y_saved = jax.lax.dynamic_index_in_dim(
                stash_d, jnp.mod(mdb_c, n_sd), keepdims=False
            )
            ctx_saved = jax.lax.dynamic_index_in_dim(
                stash_ctx, jnp.mod(mdb_c, n_sd), keepdims=False
            )
            out_rec, d_vjp = jax.vjp(dec_sec_norm, dec_stages, enc_norm, y_saved, ctx_saved)
            labels = jax.lax.dynamic_index_in_dim(labels_mbs, mdb_c, keepdims=False)
            nll, head_vjp, cnt = jax.vjp(
                lambda hs, y: _head_loss(hs, y, labels, cfg), head_sub, out_rec,
                has_aux=True,
            )
            head_mask = (is_last & db_valid).astype(jnp.float32)
            dhead_mb, dy_head = head_vjp(head_mask * scale / n_static)
            dy_in = jnp.where(is_last, dy_head, rdy_d)
            dy_in = jnp.where(db_valid, dy_in, jnp.zeros_like(dy_in))
            dw_d_mb, dnorm_mb, dy_out, dctx_out = d_vjp(dy_in.astype(dt))
            dctx_acc = dctx_out.astype(jnp.float32) + jnp.where(
                is_last, jnp.zeros_like(rdctx), rdctx
            )
            dxd = jax.lax.dynamic_update_index_in_dim(
                carry["dxd"], dy_out.astype(jnp.float32),
                jnp.where(db_valid & is_first, mdb_c, chunks), 0,
            )

            # ---- encoder backward (seeded by device 0's accumulated dctx,
            # wrapped to the last device one tick later)
            m_eb = t - (4 * pp - 2) + s
            eb_valid = (m_eb >= 0) & (m_eb < chunks)
            meb_c = jnp.clip(m_eb, 0, chunks - 1)
            xe_saved = jax.lax.dynamic_index_in_dim(
                stash_e, jnp.mod(meb_c, n_se), keepdims=False
            )
            _, e_vjp = jax.vjp(enc_section, enc_stages, xe_saved)
            dye_in = jnp.where(is_last, rbe.astype(dt), rdy_e)
            dye_in = jnp.where(eb_valid, dye_in, jnp.zeros_like(dye_in))
            dw_e_mb, dxe_out = e_vjp(dye_in)
            dxe = jax.lax.dynamic_update_index_in_dim(
                carry["dxe"], dxe_out.astype(jnp.float32),
                jnp.where(eb_valid & is_first, meb_c, chunks), 0,
            )

            new_carry = {
                "fe": out_e,
                "fd": out_d,
                "fctx": ctx_in,
                "bdy": dy_out.astype(dt),
                "bdctx": dctx_acc,
                "be": dctx_acc,  # meaningful only from device 0 via the wrap
                "bey": dxe_out.astype(dt),
                "stash_e": stash_e,
                "stash_d": stash_d,
                "stash_ctx": stash_ctx,
                "dw_e": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dw_e"], dw_e_mb
                ),
                "dw_d": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dw_d"], dw_d_mb
                ),
                "dnorm": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dnorm"], dnorm_mb
                ),
                "dhead": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), carry["dhead"], dhead_mb
                ),
                "dxe": dxe,
                "dxd": dxd,
                "loss_sum": carry["loss_sum"] + nll * head_mask,
                "tok": carry["tok"] + cnt * head_mask,
            }
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T_1f1b))
        stack = lambda tree: jax.tree.map(lambda a: a[None], tree)
        return (
            carry["loss_sum"][None],
            carry["tok"][None],
            stack(carry["dw_e"]),
            stack(carry["dw_d"]),
            stack(carry["dnorm"]),
            stack(carry["dhead"]),
            carry["dxe"][None, :chunks],
            carry["dxd"][None, :chunks],
        )

    body_1f1b_sm = compat.shard_map(
        pipeline_body_1f1b,
        mesh=mesh,
        in_specs=(P("pp"), P("pp"), P(), P(), P(), P(), P(), P()),
        out_specs=tuple([P("pp")] * 8),
        axis_names={"pp"},
        check_vma=False,
    )

    def train_step_1f1b(state, batch):
        params = state["params"]
        scale = state["scaler"]["scale"] if fp16 else jnp.ones((), jnp.float32)
        enc_tokens = batch[:, :S_e]
        dec = batch[:, S_e:]
        dec_tokens, labels = dec[:, :-1], dec[:, 1:]
        head_sub = {k: params[k] for k in head_keys}

        def embed_fn(embed_params):
            pe = {"embed": embed_params}
            xe = modeling.embed(enc_tokens, pe, cfg)
            xd = modeling.embed(dec_tokens, pe, cfg)
            return constrain(xe, mesh, full_spec), constrain(xd, mesh, full_spec)

        (xe, xd), embed_vjp = jax.vjp(embed_fn, params["embed"])
        enc_mbs = xe.reshape(chunks, mb, S_e, cfg.hidden_size)
        dec_mbs = xd.reshape(chunks, mb, S_d, cfg.hidden_size)
        labels_mbs = labels.reshape(chunks, mb, S_d)

        (loss_s, tok_s, dw_e_s, dw_d_s, dnorm_s, dhead_s, dxe_s, dxd_s) = body_1f1b_sm(
            params["enc_stages"], params["dec_stages"], params["enc_final_norm"],
            head_sub, enc_mbs, dec_mbs, labels_mbs, scale,
        )
        loss_sum = loss_s[-1]
        tok = jnp.maximum(tok_s[-1], 1.0)
        d_head = jax.tree.map(lambda a: a[-1], dhead_s)
        # enc_final_norm grads accumulate on EVERY device (each dec sub-stage
        # back-propagates through the folded norm) — sum the pp stack
        d_norm = jax.tree.map(lambda a: a.sum(axis=0), dnorm_s)
        dxe_full = dxe_s[0].reshape(global_batch_size, S_e, cfg.hidden_size)
        dxd_full = dxd_s[0].reshape(global_batch_size, S_d, cfg.hidden_size)
        (d_embed,) = embed_vjp((dxe_full.astype(xe.dtype), dxd_full.astype(xd.dtype)))

        grads: Dict[str, Any] = {
            "enc_stages": dw_e_s,
            "dec_stages": dw_d_s,
            "embed": d_embed,
            "enc_final_norm": d_norm,
        }
        for k in head_keys:
            if k == "embed":
                grads["embed"] = jax.tree.map(
                    lambda a, b: a.astype(jnp.float32) + b, grads["embed"], d_head["embed"]
                )
            else:
                grads[k] = d_head[k]
        gdenom = tok * scale / n_static
        grads = {k: jax.tree.map(lambda g: g / gdenom, v) for k, v in grads.items()}
        loss = loss_sum / tok

        if fp16:
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        new_params, new_opt = adamw_update(params, grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    train_step = (
        train_step_1f1b if hp.pipeline_type == "pipedream_flush" else gpipe_train_step
    )

    def init_state(key):
        params = init_encdec_pipeline_params(key, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(flat_params):
        params = restack_flat_encdec(flat_params, cfg, hp)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = {
        "params": encdec_param_specs(state_shape["params"], cfg, hp, axes),
        "opt": {
            "mu": encdec_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "nu": encdec_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True),
            "count": P(),
        },
        "step": P(),
    }
    if "scaler" in state_shape:
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, P(("pp",) + axes.data_axes, None))
    copts = cpu_sim_compiler_options(mesh)
    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
        compiler_options=copts,
    )
    jit_eval = jax.jit(
        lambda state, batch: loss_fn(state["params"], batch),
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
        compiler_options=copts,
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)
    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
        flatten_params=lambda sp: flatten_encdec(sp, cfg, hp),
        restack_params=lambda fp: restack_flat_encdec(fp, cfg, hp),
    )
