"""Device-mesh construction and per-layer axis assignment.

The reference materializes one NCCL process group per (tp_size, consecutive)
combination plus dual DP groups and redistribution groups between layers
(galvatron/core/comm_groups.py:58-254). On TPU we instead build ONE
``jax.sharding.Mesh`` whose non-pipeline extent is factored into **binary
axes**: world W, pipeline degree P gives mesh shape ``(P, 2, 2, ..., 2)`` with
axis names ``("pp", "x0", "x1", ..., "x{m-1}")`` where ``m = log2(W / P)``.

A layer strategy then maps to a *subset* of the binary axes:

- TP degree ``2^k`` with ``tp_consec=True`` takes the **minor** k axes
  (``x{m-k}..x{m-1}``) — adjacent device ids, the reference's "consecutive"
  rank layout which lands on the fastest ICI links; ``tp_consec=False`` takes
  the **major** k axes — strided ranks (reference: gen_tp_group_dist,
  galvatron/core/comm_groups.py:58-89).
- The complementary axes are the DP axes (dual construction, reference:
  gen_dp_group_dist, comm_groups.py:91-122).
- Context parallelism (ring attention) takes the minor axes of the DP block.

Because ``PartitionSpec`` entries accept *tuples* of axis names, a per-layer
choice of TP/DP axes is just a per-layer ``NamedSharding`` — XLA inserts the
activation resharding collectives between layers with different TP that the
reference hand-codes in galvatron/core/redistribute.py.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax

from galvatron_tpu import compat
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy


def _log2(n: int) -> int:
    k = int(round(math.log2(n)))
    if 2**k != n:
        raise ValueError(f"{n} is not a power of two")
    return k


@dataclass(frozen=True)
class MeshAxes:
    """Axis-name bookkeeping for the factored mesh."""

    pp: str
    data_axes: Tuple[str, ...]  # binary axes, major → minor

    @property
    def all_axes(self) -> Tuple[str, ...]:
        return (self.pp,) + self.data_axes

    def tp_axes(self, tp: int, consec: bool = True) -> Tuple[str, ...]:
        """Axes carrying tensor parallelism for a layer with degree ``tp``."""
        k = _log2(tp)
        if k > len(self.data_axes):
            raise ValueError(f"tp={tp} exceeds mesh data extent 2^{len(self.data_axes)}")
        if k == 0:
            return ()
        return self.data_axes[-k:] if consec else self.data_axes[:k]

    def dp_axes(self, tp: int, consec: bool = True, cp: int = 1) -> Tuple[str, ...]:
        """Axes carrying (sharded-)data parallelism: the complement of TP∪CP."""
        used = set(self.tp_axes(tp, consec)) | set(self.cp_axes(tp, consec, cp))
        return tuple(a for a in self.data_axes if a not in used)

    def cp_axes(self, tp: int, consec: bool = True, cp: int = 1) -> Tuple[str, ...]:
        """Context-parallel (ring attention) axes: minor axes of the non-TP block."""
        if cp == 1:
            return ()
        k = _log2(cp)
        rest = [a for a in self.data_axes if a not in set(self.tp_axes(tp, consec))]
        if k > len(rest):
            raise ValueError(f"cp={cp} exceeds remaining mesh extent")
        return tuple(rest[-k:])

    def ep_axes(self, tp: int, consec: bool = True, ep: int = 1) -> Tuple[str, ...]:
        """Expert-parallel axes for MoE layers: same minor-axes-of-the-non-TP-
        block selection as cp (EP subdivides data parallelism, reference:
        parallel_state.py:450-478); a strategy never uses both (strategy.py)."""
        return self.cp_axes(tp, consec, ep)


def build_mesh(
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_prefix: str = "x",
    num_slices: Optional[int] = None,
) -> Tuple[Mesh, MeshAxes]:
    """Build the factored mesh over all (or given) devices.

    Device order follows ``jax.devices()`` — on real TPU slices jax returns
    devices in torus-major order so minor mesh axes correspond to
    ICI-adjacent chips, matching the 'consecutive ranks = intra-node NVLink'
    empirical layout the reference profiles (SURVEY §5, hardware_configs).

    Multislice (DCN-connected slices; the reference's 2-node×8-GPU IB
    topology class): devices are ordered slice-major so the OUTERMOST mesh
    dims span slices — pipeline stages (which tolerate low-bandwidth p2p)
    and the major/'strided' data axes cross the DCN boundary, while
    minor/'consecutive' axes stay on ICI; the hardware profiler then
    measures DCN bandwidth for exactly the axis combinations that pay it.
    ``num_slices`` defaults to the distinct ``slice_index`` values on the
    devices (1 on single-slice systems and the CPU sim).
    """
    if devices is None:
        devices = jax.devices()
    world = len(devices)
    if world % pp != 0:
        raise ValueError(f"pp={pp} must divide world size {world}")
    if num_slices:
        # explicit request: invalid values are hard errors
        if not _is_pow2_int(num_slices):
            raise ValueError(f"num_slices must be a power of two, got {num_slices}")
        if world % num_slices:
            raise ValueError(
                f"{num_slices} slices must evenly divide the {world} devices"
            )
        devices = sorted(devices, key=_slice_key)
    else:
        # inference: reorder only when the detected slice structure is a
        # clean binary factor — otherwise keep jax's device order (device
        # subsets or exotic topologies must not break single-slice callers)
        n = len({_slice_key(d)[0] for d in devices})
        if n > 1 and _is_pow2_int(n) and world % n == 0:
            devices = sorted(devices, key=_slice_key)
    m = _log2(world // pp)
    shape = (pp,) + (2,) * m
    dev_array = np.asarray(devices).reshape(shape)
    names = ("pp",) + tuple(f"{axis_prefix}{i}" for i in range(m))
    mesh = Mesh(dev_array, names)
    return mesh, MeshAxes(pp="pp", data_axes=names[1:])


def _is_pow2_int(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def _slice_key(d) -> Tuple[int, int]:
    """Slice-major device ordering key (slice_index absent → one slice)."""
    return (getattr(d, "slice_index", 0), d.id)


def data_parallel_degree(axes: MeshAxes, s: LayerStrategy) -> int:
    return 2 ** len(axes.dp_axes(s.tp, s.tp_consec, s.cp))


def batch_spec(axes: MeshAxes, s: LayerStrategy) -> P:
    """Sharding for a (batch, seq, ...) activation entering a layer.

    Batch over DP axes always; sequence over TP axes when Megatron-SP is on
    (reference: mappings_group scatter/gather, SURVEY §2.3 'SP'), and over CP
    axes when ring attention is on.
    """
    dp = axes.dp_axes(s.tp, s.tp_consec, s.cp)
    seq_axes: Tuple[str, ...] = ()
    if s.sp:
        seq_axes += axes.tp_axes(s.tp, s.tp_consec)
    if s.cp > 1:
        seq_axes += axes.cp_axes(s.tp, s.tp_consec, s.cp)
    return P(dp or None, seq_axes or None)


def moe_token_axes(axes: MeshAxes, s: LayerStrategy) -> Tuple[str, ...]:
    """Axes sharding the flattened (B·S) token dim for MoE dispatch: the
    batch axes plus (under SP/CP) the sequence axes — the row-major
    (B, S, H) → (B·S, H) merge keeps the product sharding."""
    bs = batch_spec(axes, s)

    def flat(e) -> Tuple[str, ...]:
        if e is None:
            return ()
        return (e,) if isinstance(e, str) else tuple(e)

    return flat(bs[0]) + flat(bs[1])


def global_batch_spec(axes: MeshAxes) -> P:
    """Sharding for the raw token batch: all data axes (dataloader layout)."""
    return P(axes.data_axes or None, None)


# Curated XLA latency-hiding flag sets (--xla_overlap). 'auto' turns on the
# latency-hiding scheduler — the pass that moves collective-permute/all-gather
# starts above independent compute so the decomposed collective-matmul rings
# (ops/collective_matmul.py) and the per-layer ZeRO gradient buckets
# (sharding.overlap_grad_sync) actually overlap instead of merely being
# reorderable. 'aggressive' additionally fuses collectives into async pairs
# across multiple scheduling steps — higher compile time, occasionally better
# steady-state. Recorded verbatim in the run manifest and every BENCH metric
# line so a BENCH_r* delta is attributable to code, not scheduler drift.
XLA_OVERLAP_FLAG_SETS = {
    "off": (),
    "auto": (
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
    ),
    "aggressive": (
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
        "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
        "--xla_tpu_overlap_compute_collective_tc=true",
    ),
}


def _tpu_backend_expected() -> bool:
    """True when this process will initialize a TPU backend — decided WITHOUT
    touching jax (the flags must land in XLA_FLAGS before first backend use).
    An explicit JAX_PLATFORMS pin is authoritative; otherwise presence of
    libtpu decides. CPU/GPU backends must never see --xla_tpu_* flags: XLA
    rejects unknown flags at backend init and the process dies."""
    plat = os.environ.get("JAX_PLATFORMS", "") or os.environ.get("JAX_PLATFORM_NAME", "")
    if plat:
        return "tpu" in plat.lower()
    try:
        import importlib.util

        return importlib.util.find_spec("libtpu") is not None
    except Exception:  # noqa: BLE001 — any probe failure means "not a TPU"
        return False


def apply_xla_overlap(mode: str) -> List[str]:
    """Append the ``--xla_overlap`` mode's curated flag set to ``XLA_FLAGS``
    (idempotent — re-applying or overlapping a user-supplied flag never
    duplicates a token). Returns the flags in effect for this mode, or ``[]``
    when nothing was applied ('off', or a non-TPU backend). Must run before
    the first jax backend touch; the trainer calls it from ``train()`` and
    records mode + returned flags in the run manifest."""
    if mode not in XLA_OVERLAP_FLAG_SETS:
        raise ValueError(
            f"xla_overlap must be one of {sorted(XLA_OVERLAP_FLAG_SETS)}, got {mode!r}"
        )
    flags = XLA_OVERLAP_FLAG_SETS[mode]
    if not flags or not _tpu_backend_expected():
        return []
    toks = os.environ.get("XLA_FLAGS", "").split()
    for f in flags:
        if f not in toks:
            toks.append(f)
    os.environ["XLA_FLAGS"] = " ".join(toks)
    return list(flags)


def ambient_or(mesh):
    """Mesh to hand a nested ``shard_map``: inside a manual region (the pp>1
    pipeline runs stages under a manual-'pp' shard_map) a nested shard_map
    must be given the ambient AbstractMesh — whose manual axes are marked
    Manual — not the original concrete mesh, or tracing fails with an
    axis-type mismatch. Load-bearing for every cp impl (ring/a2a) at pp>1."""
    am = compat.get_abstract_mesh()
    types = getattr(am, "axis_types", None) or ()
    if any(t == compat.AxisType.Manual for t in types):
        return am
    return mesh


def manual_axis_names(am) -> set:
    """Every ambient-mesh axis not already Manual — the axis_names set a
    nested shard_map wrapping a Mosaic kernel must manualize. Any axis left
    auto — including a size-1 'pp' axis at pp=1 or the dp axes carrying the
    batch — keeps the body under GSPMD, which cannot partition Mosaic custom
    calls on a real multi-chip TPU ("Mosaic kernels cannot be automatically
    partitioned"; caught by tests/test_topology_aot.py — CPU interpret-mode
    kernels never surface it). Axes already Manual (the pp engines' 'pp')
    must not be re-bound."""
    types = getattr(am, "axis_types", None) or ()
    manual = {
        n for n, t in zip(am.axis_names, types)
        if t != compat.AxisType.Manual
    }
    return manual or set(am.axis_names)
