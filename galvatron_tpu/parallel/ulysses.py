"""Ulysses-style context parallelism: sequence all-to-all over ICI.

The second long-context capability beyond the reference (SURVEY §2.3: no
CP/ring/Ulysses anywhere in Galvatron) and the alternative to ring attention
(galvatron_tpu.parallel.ring): instead of rotating K/V blocks around a ring,
one ``all_to_all`` re-shards activations from sequence-sharded to
head-sharded, each device runs *full-sequence* attention for its head subset
(on TPU: the Pallas flash kernel), and a second ``all_to_all`` restores
sequence sharding.

Trade-off vs ring (why both exist): Ulysses moves 2×(q+k+v+o)/cp bytes in two
bursty all-to-alls and keeps the attention core un-tiled (best when heads ≥
cp and the MXU-friendly full-length kernel wins); ring moves k+v per step
overlapped with compute and has no head-count constraint (best at extreme
sequence lengths or few heads). The strategy dimension ``cp_impl`` selects
per layer.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax

from galvatron_tpu import compat
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import ambient_or, manual_axis_names


def _a2a_attn_local(q, k, v, cfg: ModelConfig, axis_name, cp: int):
    """Runs inside shard_map with ``axis_name`` manual. q local:
    (B, S/cp, n, d) sequence-sharded; k/v may still be at kv_heads — the
    attention core GQA-repeats after the all-to-all, so grouped K/V cross the
    CP axes at 1/group_factor of the repeated volume."""
    # seq-sharded → head-sharded: (B, S/cp, n, d) → (B, S, n/cp, d)
    q = jax.lax.all_to_all(q, axis_name, 2, 1, tiled=True)
    k = jax.lax.all_to_all(k, axis_name, 2, 1, tiled=True)
    v = jax.lax.all_to_all(v, axis_name, 2, 1, tiled=True)
    o = modeling.attention(q, k, v, cfg)  # full-sequence causal core
    # head-sharded → seq-sharded
    return jax.lax.all_to_all(o, axis_name, 1, 2, tiled=True)


def ulysses_attention(
    q, k, v, cfg: ModelConfig, mesh: Mesh, cp_axes: Sequence[str],
    batch_axes: Sequence[str] = (), head_axes: Sequence[str] = (),
):
    """q/k/v: (B, S, n, d) global arrays, sequence sharded over ``cp_axes``;
    n must be divisible by the CP degree (the Ulysses head constraint).
    ``batch_axes``/``head_axes``: the layer's dp/tp axes — the region is
    fully manual (see mesh.manual_axis_names: GSPMD cannot partition the
    Mosaic attention core on a real multi-chip TPU), so the batch/head dims
    must carry their sharding explicitly."""
    cp = int(np.prod([mesh.shape[a] for a in cp_axes]))
    tp = int(np.prod([mesh.shape[a] for a in head_axes])) if head_axes else 1
    # the head dim is tp-sharded inside the manual region, so the a2a splits
    # the tp-LOCAL head count — validate that, not the global one
    if q.shape[2] % tp or (q.shape[2] // tp) % cp:
        raise ValueError(
            f"cp_impl='a2a' needs the tp-local head count "
            f"{q.shape[2]}/tp={tp} divisible by cp={cp} "
            "(use cp_impl='ring' for few-head models)"
        )
    kv = k.shape[2]
    if kv % tp or (kv // tp) % cp:  # grouped K/V can't split over tp×cp — repeat
        k = modeling._repeat_kv(k, q.shape[2] // kv)
        v = modeling._repeat_kv(v, q.shape[2] // kv)
    if cfg.attn_impl == "ring":  # never recurse into the ring dispatch
        cfg = cfg.replace(attn_impl="xla")
    axis = tuple(cp_axes)
    spec = P(tuple(batch_axes) or None, axis, tuple(head_axes) or None, None)
    mesh = ambient_or(mesh)
    fn = compat.shard_map(
        functools.partial(_a2a_attn_local, cfg=cfg, axis_name=axis, cp=cp),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual_axis_names(mesh),
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_decoder_layer(
    x, p, cfg: ModelConfig, mesh, cp_axes, cos_sin,
    batch_axes: Sequence[str] = (), head_axes: Sequence[str] = (),
):
    """Decoder layer with the attention core Ulysses-parallelized (drop-in for
    modeling.decoder_layer when a layer strategy sets cp > 1, cp_impl='a2a').
    Projections and RoPE run at the global level (GSPMD shards them over the
    sequence); only the core crosses the CP axes."""

    def attn(xn):
        b, s, h = xn.shape
        hd = cfg.head_dim
        q, k, v = modeling.project_qkv_heads(xn, p["attn"], cfg)
        if cfg.pos_embed == "rope":
            cos, sin = cos_sin
            q = modeling.apply_rope(q, cos, sin)
            k = modeling.apply_rope(k, cos, sin)
        # K/V stay at kv_heads across the all-to-all (GQA repeat happens in
        # the local attention core) — group_factor× less CP traffic
        o = modeling._constrain_attn_out(
            ulysses_attention(
                q, k, v, cfg, mesh, cp_axes,
                batch_axes=batch_axes, head_axes=head_axes,
            ),
            cfg,
        )
        return modeling.attn_output(o, p["attn"], cfg, xn.dtype)

    x = x + attn(modeling.norm(x, p["attn_norm"], cfg))
    x = x + modeling.mlp_block(modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg)
    return x
