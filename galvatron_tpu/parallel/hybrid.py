"""Hybrid-parallel runtime: build and execute a layer-heterogeneous strategy.

The TPU-native equivalent of the reference's 7-step model construction
(construct_hybrid_parallel_model_api, galvatron/core/hybrid_parallel_model.py:81-153):

  reference step                         → here
  [0] gen_comm_groups                    → build_mesh (one Mesh, binary axes)
  [1] construct_tensor_parallel_model    → per-layer param specs ('tp' dims)
  [2] construct_sequential_model         → the model is already functional
  [3] wrap relocation modules            → with_sharding_constraint per layer
  [4] PipelineParallel stage placement   → galvatron_tpu.parallel.pipeline
  [5] per-layer FSDP wrapping            → 'fsdp' dims in param/opt specs
  [6] per-layer checkpoint wrapping      → jax.checkpoint per layer

``HybridParallelRuntime`` owns the jitted ``train_step`` (the
GalvatronModel.forward_backward equivalent, reference:
galvatron/core/hybrid_parallel_model.py:15-35), dispatching between the
no-pipeline GSPMD path (pp=1, with optional micro-batch gradient
accumulation) and the shard_map pipeline schedules (pp>1).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.core.optim import (
    AdamConfig,
    adamw_update,
    apply_update_with_scaler,
    init_opt_state,
)
from galvatron_tpu.core.schedules import (
    LossScalerConfig,
    init_scaler_state,
    scaled_value_and_grad,
)
from galvatron_tpu.core.strategy import HybridParallelConfig, LayerStrategy
from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import (
    MeshAxes,
    batch_spec,
    build_mesh,
    global_batch_spec,
    moe_token_axes,
)
from galvatron_tpu.parallel.sharding import (
    constrain,
    cp_shard_axes,
    overlap_grad_sync,
    param_spec,
    sharding_tree,
    with_flash_shard_ctx,
    with_tp_overlap_ctx,
)


def activation_spec(axes: MeshAxes, s: LayerStrategy) -> P:
    """(B, S, H) activation spec at a layer boundary."""
    bs = batch_spec(axes, s)
    return P(bs[0], bs[1], None)


def model_param_specs(
    params_shape: Any, cfg: ModelConfig, hp: HybridParallelConfig, axes: MeshAxes,
    *, for_opt_state: bool = False,
) -> Any:
    """Spec tree for the whole model: per-layer strategies for the decoder
    layers, vocab_tp/embed_dp for embedding+head+final norm (reference:
    hp_config_whole_model, galvatron/core/hybrid_parallel_config.py:141-179)."""
    annots = modeling.model_annotations(cfg)
    embed_strategy = LayerStrategy(
        tp=hp.vocab_tp, tp_consec=True, dp_type=hp.embed_dp_type, sp=hp.vocab_sp
    )
    ps = lambda leaf, a, s: param_spec(leaf.shape, a, axes, s, for_opt_state=for_opt_state)
    specs: Dict[str, Any] = {}
    is_leaf = lambda x: hasattr(x, "shape")
    E = cfg.enc_layers  # strategy indices: encoder stack first, then decoder
    for key in params_shape:
        if key == "enc_layers":
            specs["enc_layers"] = [
                jax.tree.map(
                    functools.partial(ps, s=hp.layer_strategies[i]),
                    params_shape["enc_layers"][i],
                    annots["enc_layers"][i],
                    is_leaf=is_leaf,
                )
                for i in range(len(params_shape["enc_layers"]))
            ]
        elif key == "layers":
            specs["layers"] = [
                jax.tree.map(
                    functools.partial(ps, s=hp.layer_strategies[E + i]),
                    params_shape["layers"][i],
                    annots["layers"][i],
                    is_leaf=is_leaf,
                )
                for i in range(len(params_shape["layers"]))
            ]
        else:
            specs[key] = jax.tree.map(
                functools.partial(ps, s=embed_strategy),
                params_shape[key],
                annots[key],
                is_leaf=is_leaf,
            )
    return specs


def state_specs(state_shape, cfg, hp, axes):
    """Specs for the full train state {params, opt{mu,nu,count}, step}."""
    pspec = model_param_specs(state_shape["params"], cfg, hp, axes)
    ospec = model_param_specs(state_shape["params"], cfg, hp, axes, for_opt_state=True)
    specs = {
        "params": pspec,
        "opt": {"mu": ospec, "nu": ospec, "count": P()},
        "step": P(),
    }
    if "scaler" in state_shape:  # fp16 dynamic loss scale: replicated scalars
        specs["scaler"] = jax.tree.map(lambda _: P(), state_shape["scaler"])
    return specs


@dataclass
class HybridParallelRuntime:
    """Executable hybrid-parallel model (GalvatronModel equivalent)."""

    cfg: ModelConfig
    hp: HybridParallelConfig
    mesh: Mesh
    axes: MeshAxes
    adam: AdamConfig
    train_step: Callable  # (state, batch) -> (state, loss)
    eval_loss: Callable  # (state, batch) -> loss
    init_state: Callable  # (key) -> state
    state_shardings: Any
    batch_sharding: Any = None  # NamedSharding of the token batch
    # (flat model param tree) -> fresh state carrying those weights — the
    # pretrained-weight entry point (e.g. models/convert.py HF import). The
    # pipeline runtime restacks transformer layers per stage first.
    init_state_from: Callable = None
    # portable-checkpoint layout transforms (None = params are already flat):
    # flatten_params: engine layout -> flat {layers: [...]} tree;
    # restack_params: the inverse. Checkpoints are always SAVED flat so
    # resume works across pipeline degrees/schedules (core/checkpoint.py).
    flatten_params: Callable = None
    restack_params: Callable = None

    def shard_batch(self, batch_np):
        """Global on-device batch from a (host-replicated) numpy batch.

        Single-process: a device_put. Multi-host (TPU pods over DCN): every
        process runs the same deterministic loader, and
        ``jax.make_array_from_callback`` materializes only the rows this
        process's addressable devices own — the distributed data path the
        reference gets from DistributedSampler + NCCL
        (utils/training_utils.py:14-23)."""
        import numpy as _np

        batch_np = _np.asarray(batch_np)
        if self.batch_sharding is None or jax.process_count() == 1:
            if self.batch_sharding is None:
                return jnp.asarray(batch_np)
            return jax.device_put(batch_np, self.batch_sharding)
        return jax.make_array_from_callback(
            batch_np.shape, self.batch_sharding, lambda idx: batch_np[idx]
        )


def _make_layer_hook(cfg: ModelConfig, hp: HybridParallelConfig, mesh: Mesh, axes: MeshAxes):
    """Per-layer execution hook: sharding-constraint boundary (redistribution)
    + optional remat (checkpoint_wrapper) + ring-attention dispatch."""

    # async ZeRO gradient overlap (sharding.overlap_grad_sync): the hook pins
    # each zero2/zero3 layer's param cotangents to their reduce-scattered
    # sharding, so the per-layer gradient buckets issue during backward
    grad_annots = modeling.model_annotations(cfg) if hp.grad_overlap else None

    def hook(i: int, x, lp, enc_out=None, seg_ids=None):
        s = hp.layer_strategies[i]
        x = constrain(x, mesh, activation_spec(axes, s))
        layer_cfg = cfg
        if s.ckpt == "full" and cfg.mlp_recompute != "off":
            # full-layer remat saves only the layer boundary — a nested
            # gate-save policy inside the remat region is pure overhead
            layer_cfg = layer_cfg.replace(mlp_recompute="off")
        if s.cp > 1 and s.cp_impl == "ring":
            layer_cfg = layer_cfg.replace(attn_impl="ring")
        if cfg.moe_experts > 0 and s.ep > 1:
            layer_cfg = layer_cfg.replace(
                moe_shard_ctx=(
                    mesh,
                    axes.ep_axes(s.tp, s.tp_consec, s.ep),
                    moe_token_axes(axes, s),
                )
            )
        if s.dp_type == "zero3" and s.tp > 1:
            # fsdp x tp wgrad shardings trip an SPMD partitioner fallback
            # (involuntary full remat of dy) without this pin — see
            # modeling._constrain_attn_out
            layer_cfg = layer_cfg.replace(
                attn_out_shard_ctx=(mesh, axes.dp_axes(s.tp, s.tp_consec, s.cp))
            )
        if s.tp > 1:
            # pin the stacked qkv (and its dqkv cotangent) — see
            # modeling._constrain_qkv
            layer_cfg = layer_cfg.replace(
                qkv_shard_ctx=(
                    mesh,
                    axes.dp_axes(s.tp, s.tp_consec, s.cp),
                    axes.tp_axes(s.tp, s.tp_consec),
                )
            )
        # Mosaic kernels cannot be auto-partitioned by GSPMD — see
        # sharding.with_flash_shard_ctx / modeling._flash_shard_map
        layer_cfg = with_flash_shard_ctx(layer_cfg, s, mesh, axes)
        # decomposed collective-matmul on the TP projection seams — see
        # sharding.with_tp_overlap_ctx / ops.collective_matmul
        layer_cfg = with_tp_overlap_ctx(layer_cfg, s, mesh, axes)
        if layer_cfg.pos_embed == "rope":
            # packed rows: per-segment position reset → per-row gathered tables
            cos_sin = (
                modeling.packed_rope_tables(
                    layer_cfg, modeling.positions_from_segments(seg_ids)
                )
                if seg_ids is not None
                else modeling.rope_tables(layer_cfg, x.shape[1])
            )
        else:
            cos_sin = None
        alibi = (
            jnp.asarray(modeling.alibi_slopes(layer_cfg.num_heads))
            if layer_cfg.pos_embed == "alibi"
            else None
        )
        is_encoder = cfg.enc_layers > 0 and i < cfg.enc_layers
        if grad_annots is not None and s.dp_type in ("zero2", "zero3"):
            la = (
                grad_annots["enc_layers"][i]
                if is_encoder
                else grad_annots["layers"][i - cfg.enc_layers]
            )
            lp = overlap_grad_sync(lp, la, mesh, axes, s)

        def run(x_, lp_):
            if cfg.swin_depths:
                return modeling.swin_layer(
                    x_, lp_, cfg, i, remat_attn=(s.ckpt == "selective")
                )
            if is_encoder:
                return modeling.encoder_layer(
                    x_, lp_, layer_cfg, cos_sin, remat_attn=(s.ckpt == "selective")
                )
            if s.cp > 1:
                cp_axes = axes.cp_axes(s.tp, s.tp_consec, s.cp)
                cp_kw = cp_shard_axes(s, axes)
                if s.cp_impl == "a2a":
                    from galvatron_tpu.parallel.ulysses import ulysses_decoder_layer

                    return ulysses_decoder_layer(
                        x_, lp_, layer_cfg, mesh, cp_axes, cos_sin, **cp_kw
                    )
                from galvatron_tpu.parallel.ring import ring_decoder_layer

                return ring_decoder_layer(
                    x_, lp_, layer_cfg, mesh, cp_axes, cos_sin, **cp_kw
                )
            return modeling.decoder_layer(
                x_, lp_, layer_cfg, cos_sin, alibi,
                remat_attn=(s.ckpt == "selective"), enc_out=enc_out,
                seg_ids=seg_ids,
            )

        if s.ckpt == "full":
            run = jax.checkpoint(run)
        return run(x, lp)

    return hook


def build_runtime(
    cfg: ModelConfig,
    hp: HybridParallelConfig,
    mesh: Optional[Mesh] = None,
    axes: Optional[MeshAxes] = None,
    adam: AdamConfig = AdamConfig(),
    global_batch_size: int = 8,
    seq_len: Optional[int] = None,
) -> HybridParallelRuntime:
    """Construct the jitted train/eval step for (model config, hybrid strategy).

    pp=1 → pure-GSPMD path with optional micro-batch grad accumulation
    (the no_pipeline_forward_backward equivalent, reference:
    galvatron/core/pipeline/pipeline.py:173-235); pp>1 → shard_map pipeline
    (galvatron_tpu.parallel.pipeline).
    """
    if mesh is None:
        mesh, axes = build_mesh(pp=hp.pp)
    assert axes is not None
    if hp.num_layers != cfg.total_layers:
        raise ValueError(
            f"strategy has {hp.num_layers} layer entries but model has "
            f"{cfg.total_layers} (encoder + decoder) layers"
        )
    hp.validate(mesh.devices.size)
    if not cfg.causal and any(s.cp > 1 for s in hp.layer_strategies):
        raise ValueError(
            "context parallelism (cp>1) is causal-only (ring/Ulysses kernels "
            "assume a causal mask); encoder models must use tp/sp instead"
        )
    if cfg.enc_layers > 0:
        if any(s.cp > 1 for s in hp.layer_strategies):
            raise ValueError("context parallelism is not supported for enc-dec models")
    if cfg.pack_sequences:
        # packed sequences (galvatron_tpu.data): supported on the GSPMD path
        # and the gpipe/1F1B stage-stacked pipelines. Everything the segment
        # mask cannot reach is refused loudly rather than silently attending
        # across documents.
        if cfg.objective != "clm" or cfg.enc_layers or cfg.image_size:
            raise ValueError(
                "pack_sequences requires a decoder-only CLM model "
                "(enc-dec / vision / mlm rows carry no segment layout)"
            )
        if cfg.attn_impl != "xla":
            raise ValueError(
                "pack_sequences requires attn_impl='xla': the flash/ring "
                "Pallas kernels carry no segment mask, and running them would "
                "silently attend across packed documents"
            )
        if any(s.cp > 1 for s in hp.layer_strategies):
            raise ValueError(
                "pack_sequences is incompatible with context parallelism "
                "(ring/Ulysses assume a plain causal mask)"
            )
        if hp.pp > 1 and hp.vpp > 1:
            raise ValueError(
                "pack_sequences is not threaded through the interleaved "
                "(vpp>1) schedule; use vpp=1 pipelines"
            )
    seq_len = seq_len or cfg.sample_len

    # the strategy's activation-recompute mode rides the model config so
    # every execution path (GSPMD hook, all pipeline engines, the head/loss
    # seams) sees the same policy
    if cfg.mlp_recompute != hp.mlp_recompute:
        cfg = cfg.replace(mlp_recompute=hp.mlp_recompute)
    if cfg.dtype != jnp.float32 and hp.mixed_precision == "fp32":
        cfg = cfg.replace(dtype=jnp.float32)
    if hp.mixed_precision == "bf16" and cfg.dtype == jnp.float32:
        cfg = cfg.replace(dtype=jnp.bfloat16)
    # fp16 parity path (reference: --mixed_precision fp16, core/arguments.py:
    # 104-106 + megatron grad_scaler): fp16 compute, fp32 master params,
    # dynamic loss scaling with skip-on-overflow. bf16 is the TPU-native
    # choice; fp16 exists so reference configs port unchanged.
    fp16 = hp.mixed_precision == "fp16"
    if fp16:
        cfg = cfg.replace(dtype=jnp.float16)
        scaler_cfg = LossScalerConfig()

    if hp.pp > 1:
        if cfg.swin_depths:
            from galvatron_tpu.parallel.pipeline_swin import (
                build_swin_pipeline_runtime,
            )

            return build_swin_pipeline_runtime(
                cfg, hp, mesh, axes, adam, global_batch_size, seq_len
            )
        if cfg.enc_layers > 0:
            from galvatron_tpu.parallel.pipeline_encdec import (
                build_encdec_pipeline_runtime,
            )

            return build_encdec_pipeline_runtime(
                cfg, hp, mesh, axes, adam, global_batch_size, seq_len
            )
        from galvatron_tpu.parallel.pipeline import build_pipeline_runtime

        return build_pipeline_runtime(cfg, hp, mesh, axes, adam, global_batch_size, seq_len)

    hook = _make_layer_hook(cfg, hp, mesh, axes)

    def loss_fn(params, tokens_batch):
        return modeling.lm_loss(params, tokens_batch, cfg, layer_hook=hook)

    chunks = max(1, hp.chunks)
    if global_batch_size % chunks != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by chunks {chunks}"
        )

    def grads_fn(params, batch, scale=None):
        """(loss, grads); with ``scale`` (fp16) the backward runs on
        ``loss * scale`` and grads are returned unscaled in fp32."""
        if chunks == 1:
            if scale is None:
                return jax.value_and_grad(loss_fn)(params, batch)
            return scaled_value_and_grad(loss_fn, scale)(params, batch)
        # micro-batch gradient accumulation via scan (chunk_batch equivalent,
        # reference: galvatron/core/pipeline/utils.py:9-36). Accumulates
        # (nll_sum, token_count) so the result equals the unchunked global
        # token-mean even with uneven ignore_index masks per chunk.
        b = batch.shape[0]
        assert b % chunks == 0, f"global batch {b} not divisible by chunks {chunks}"
        mbs = batch.reshape(chunks, b // chunks, *batch.shape[1:])

        def sum_fn(params, mb):
            s, n = modeling.lm_loss_sum(params, mb, cfg, layer_hook=hook)
            return s, n

        # fp16: seed on the mean-equivalent loss (sum / static token count) so
        # cotangent magnitudes match the unchunked mean-loss path — a raw
        # sum-loss seed multiplies O(1) per-token cotangents by the full scale
        # and overflows fp16 immediately at the 2^16 initial scale
        n_static = (b // chunks) * modeling.loss_tokens_per_sample(cfg, batch.shape[1] - 1)

        def body(acc, mb):
            if scale is None:
                (s, n), g = jax.value_and_grad(sum_fn, has_aux=True)(params, mb)
            else:

                def scaled(p, mb_):
                    s_, n_ = sum_fn(p, mb_)
                    return s_ * (scale / n_static), (s_, n_)

                (_, (s, n)), g = jax.value_and_grad(scaled, has_aux=True)(params, mb)
            acc_s, acc_n, acc_g = acc
            return (acc_s + s, acc_n + n, jax.tree.map(jnp.add, acc_g, g)), None

        zero = (
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.int32),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
        )
        (tot_s, tot_n, tot_g), _ = jax.lax.scan(body, zero, mbs)
        denom = jnp.maximum(tot_n, 1).astype(jnp.float32)
        gdenom = denom if scale is None else denom * scale / n_static
        return tot_s / denom, jax.tree.map(lambda g: g / gdenom, tot_g)

    def train_step(state, batch):
        if fp16:
            loss, grads = grads_fn(state["params"], batch, state["scaler"]["scale"])
            return apply_update_with_scaler(state, loss, grads, adam, scaler_cfg)
        loss, grads = grads_fn(state["params"], batch)
        new_params, new_opt = adamw_update(state["params"], grads, state["opt"], adam)
        return {"params": new_params, "opt": new_opt, "step": state["step"] + 1}, loss

    def init_state(key):
        params = modeling.init_model_params(key, cfg)
        state = {"params": params, "opt": init_opt_state(params), "step": jnp.zeros((), jnp.int32)}
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    def state_from(params):
        state = {
            "params": params,
            "opt": init_opt_state(params),
            "step": jnp.zeros((), jnp.int32),
        }
        if fp16:
            state["scaler"] = init_scaler_state(scaler_cfg)
        return state

    # shardings
    state_shape = jax.eval_shape(init_state, jax.random.key(0))
    specs = state_specs(state_shape, cfg, hp, axes)
    shardings = sharding_tree(mesh, specs)
    batch_sharding = NamedSharding(mesh, global_batch_spec(axes))

    jit_train = jax.jit(
        train_step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    jit_eval = jax.jit(
        lambda state, batch: loss_fn(state["params"], batch),
        in_shardings=(shardings, batch_sharding),
        out_shardings=NamedSharding(mesh, P()),
    )
    jit_init = jax.jit(init_state, out_shardings=shardings)
    jit_state_from = jax.jit(state_from, out_shardings=shardings)

    return HybridParallelRuntime(
        cfg=cfg, hp=hp, mesh=mesh, axes=axes, adam=adam,
        train_step=jit_train, eval_loss=jit_eval, init_state=jit_init,
        state_shardings=shardings, batch_sharding=batch_sharding,
        init_state_from=jit_state_from,
    )


# --- AOT program registration (galvatron_tpu/aot): the trainer family -------
# One family covers EVERY engine build_runtime can dispatch to (GSPMD hybrid,
# gpipe/1F1B/interleaved shard_map pipelines, enc-dec, swin): they all expose
# the same jitted (state, batch) train_step / eval_loss seam, so the set of
# programs a plan needs is enumerable here with no data and no compile.


def _trainer_programs(ctx):
    import jax.numpy as _jnp

    from galvatron_tpu.aot.registry import ProgramSpec
    from galvatron_tpu.core.checkpoint import abstract_state_of

    rt = ctx.runtime
    if rt is None:
        rt = build_runtime(
            ctx.cfg, ctx.hp, mesh=ctx.mesh, axes=ctx.axes,
            adam=ctx.adam if ctx.adam is not None else AdamConfig(),
            global_batch_size=ctx.global_bsz, seq_len=ctx.seq_len,
        )
    state_abs = abstract_state_of(rt)
    seq = ctx.seq_len or rt.cfg.sample_len
    # the loader row contract lives in modeling.batch_row_width (packed rows
    # are 2·(S+1), not S+1) — same aval the fidelity harness lowers against
    # (search/memory_fidelity.measured_train_mb); a wrong width here would
    # warm a program the run never dispatches and wrongly drop the
    # watchdog's first-step compile grace
    batch_abs = jax.ShapeDtypeStruct(
        (ctx.global_bsz, modeling.batch_row_width(rt.cfg, seq)),
        _jnp.int32,
        sharding=rt.batch_sharding,
    )
    engine = "pipeline" if rt.hp.pp > 1 else "gspmd"
    # optimizer hyperparameters are CONSTANTS inside the compiled step — a
    # different lr/schedule is a different program, so they join the key;
    # exec_cfg is the runtime's EXECUTED config (build_runtime rewrites
    # dtype/mlp_recompute from the plan), the one both the trainer consult
    # and the elastic prewarm must key on to agree
    key_extra = {"adam": repr(rt.adam), "engine": engine}
    specs = [
        ProgramSpec(
            "train_step", rt.train_step, (state_abs, batch_abs),
            meta={"donate": (0,), "engine": engine, "key_extra": key_extra,
                  "exec_cfg": rt.cfg},
        ),
        ProgramSpec(
            "eval_loss", rt.eval_loss, (state_abs, batch_abs),
            meta={"engine": engine, "key_extra": {"engine": engine},
                  "exec_cfg": rt.cfg},
        ),
    ]
    if hasattr(rt.init_state, "lower"):  # some pipeline engines init host-side
        key_abs = jax.eval_shape(lambda: jax.random.key(0))
        specs.append(
            ProgramSpec("init_state", rt.init_state, (key_abs,),
                        meta={"engine": engine, "exec_cfg": rt.cfg,
                              "key_extra": {"engine": engine}})
        )
    return specs


def _register_aot_programs():
    from galvatron_tpu.aot.registry import register_program

    register_program(
        "trainer", _trainer_programs, needs_plan=True,
        programs=("train_step", "eval_loss", "init_state"),
    )


_register_aot_programs()
