"""Ring attention: context parallelism over an ICI ring.

A first-class long-context capability the reference lacks entirely (SURVEY
§2.3: no CP/ring/Ulysses anywhere in Galvatron; its long-context story is
Megatron-SP + FlashAttention + ckpt only). Sequence is sharded over the CP
mesh axes; K/V blocks rotate around the ring via ``lax.ppermute`` while each
device accumulates its queries' attention with online softmax — O(S/cp)
activation memory per device, exact causal attention.

Schedule: step 0 attends to the local (diagonal) K/V block, so the running
max starts finite; later steps mask by global position (blocks entirely in
the future contribute exp(-inf - m) = 0, never NaN).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig

NEG_INF = -1e30


def _ring_attn_local(q, k, v, axis_name: str, cp: int, sm_scale: float):
    """Runs inside shard_map with ``axis_name`` manual. q/k/v local:
    (B, S/cp, n, d), sequence sharded in ring order."""
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    perm = [(i, (i + 1) % cp) for i in range(cp)]  # kv block i → device i+1

    q32 = q.astype(jnp.float32)
    rows = idx * s_local + jnp.arange(s_local)  # global q positions

    def step(carry, step_idx):
        k_cur, v_cur, m, l, acc = carry
        owner = (idx - step_idx) % cp  # whose kv block we currently hold
        cols = owner * s_local + jnp.arange(s_local)
        scores = (
            jnp.einsum("bqnh,bknh->bnqk", q32, k_cur.astype(jnp.float32)) * sm_scale
        )
        mask = rows[:, None] >= cols[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        acc_new = alpha[..., None] * acc + jnp.einsum(
            "bnqk,bknh->bnqh", p, v_cur.astype(jnp.float32)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    b, _, n, d = q.shape
    m0 = jnp.full((b, n, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s_local), jnp.float32)
    acc0 = jnp.zeros((b, n, s_local, d), jnp.float32)
    (k, v, m, l, acc), _ = jax.lax.scan(step, (k, v, m0, l0, acc0), jnp.arange(cp))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B, S/cp, n, d)


def ring_attention(
    q, k, v, mesh: Mesh, cp_axes: Sequence[str], sm_scale: float | None = None
):
    """q/k/v: (B, S, n, d) global arrays; sequence ring-sharded over cp_axes."""
    cp = int(np.prod([mesh.shape[a] for a in cp_axes]))
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    axis = tuple(cp_axes)
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_attn_local, axis_name=axis, cp=cp, sm_scale=sm_scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=set(cp_axes),
        check_vma=False,
    )
    return fn(q, k, v)


def ring_decoder_layer(x, p, cfg: ModelConfig, mesh, cp_axes, cos_sin):
    """Decoder layer with the attention core ring-parallelized (drop-in for
    modeling.decoder_layer when a layer strategy sets cp > 1)."""

    def attn(xn):
        b, s, h = xn.shape
        hd = cfg.head_dim
        q, k, v = modeling.split_qkv(xn @ p["attn"]["wqkv"].astype(xn.dtype), cfg)
        if cfg.pos_embed == "rope":
            cos, sin = cos_sin
            q = modeling.apply_rope(q, cos, sin)
            k = modeling.apply_rope(k, cos, sin)
        k = modeling._repeat_kv(k, cfg.num_heads // k.shape[2])
        v = modeling._repeat_kv(v, cfg.num_heads // v.shape[2])
        o = ring_attention(q, k, v, mesh, cp_axes)
        return o.reshape(b, s, cfg.num_heads * hd) @ p["attn"]["wo"].astype(xn.dtype)

    x = x + attn(modeling.norm(x, p["attn_norm"], cfg))
    x = x + modeling.mlp_block(modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg)
    return x
