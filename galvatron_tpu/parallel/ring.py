"""Ring attention: context parallelism over an ICI ring.

A first-class long-context capability the reference lacks entirely (SURVEY
§2.3: no CP/ring/Ulysses anywhere in Galvatron; its long-context story is
Megatron-SP + FlashAttention + ckpt only). Sequence is sharded over the CP
mesh axes; K/V blocks rotate around the ring via ``lax.ppermute`` while each
device accumulates its queries' attention with online softmax — O(S/cp)
activation memory per device, exact causal attention.

Schedule: step 0 attends to the local (diagonal) K/V block, so the running
max starts finite; later steps mask by global position (blocks entirely in
the future contribute exp(-inf - m) = 0, never NaN).

Two per-hop compute paths:

- **Pallas flash blocks** (``_ring_flash``, the default when the local
  sequence tiles): each hop runs the flash-attention forward kernel on the
  resident K/V block (causal on the diagonal hop, unmasked on past hops,
  skipped on future hops) and folds the block's normalized output into a
  running (max, sum, acc) via its log-sum-exp. The backward is a second ring
  pass over the flash dq/dkv kernels with the GLOBAL lse/delta — the flash
  decomposition makes per-block gradient contributions independent once the
  per-row statistics are fixed; dk/dv accumulators rotate with their K/V
  block and arrive home after cp hops.
- **einsum fallback** (``_ring_attn_local``) for shapes that don't tile.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax

from galvatron_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.models import modeling
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.parallel.mesh import ambient_or, manual_axis_names
from galvatron_tpu.ops.flash_attention import (
    _flash_bwd_parts,
    _flash_fwd,
    _use_interpret,
)

NEG_INF = -1e30


def _ring_attn_local(q, k, v, idx_arr, axis_name: str, cp: int, sm_scale: float):
    """Runs inside shard_map with ``axis_name`` manual. q/k/v local:
    (B, S/cp, n, d), sequence sharded in ring order; ``idx_arr`` is this
    shard's slice of arange(cp) (the ring position)."""
    idx = idx_arr[0]
    s_local = q.shape[1]
    perm = [(i, (i + 1) % cp) for i in range(cp)]  # kv block i → device i+1

    q32 = q.astype(jnp.float32)
    rows = idx * s_local + jnp.arange(s_local)  # global q positions

    def accum(carry, k_cur, v_cur, owner):
        m, l, acc = carry
        cols = owner * s_local + jnp.arange(s_local)
        scores = (
            jnp.einsum("bqnh,bknh->bnqk", q32, k_cur.astype(jnp.float32)) * sm_scale
        )
        mask = rows[:, None] >= cols[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        acc_new = alpha[..., None] * acc + jnp.einsum(
            "bnqk,bknh->bnqh", p, v_cur.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    b, _, n, d = q.shape
    m0 = jnp.full((b, n, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s_local), jnp.float32)
    acc0 = jnp.zeros((b, n, s_local, d), jnp.float32)
    # hop 0: the local (diagonal) block — no rotation needed; scan steps
    # permute first, then compute, so no hop rotates K/V just to discard it
    carry0 = accum((m0, l0, acc0), k, v, idx)

    def step(carry, step_idx):
        k_cur, v_cur, mla = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        owner = (idx - step_idx) % cp  # whose kv block we now hold
        return (k_cur, v_cur, accum(mla, k_cur, v_cur, owner)), None

    (_, _, (m, l, acc)), _ = jax.lax.scan(step, (k, v, carry0), jnp.arange(1, cp))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B, S/cp, n, d)


# ---------------------------------------------------------------------------
# Flash-block ring (Pallas kernels per hop, custom VJP)
# ---------------------------------------------------------------------------


def _ring_block(is_past, q, k_cur, v_cur, sm_scale, block_q, block_k, interpret):
    """(fp32 out, lse) of q against a non-diagonal resident K/V block:
    unmasked when the block is in the past, nothing (lse = -inf) when it is
    in the future. The diagonal (locally causal) hop runs outside the scan."""

    def past(q, k_, v_):
        return _flash_fwd(
            q, k_, v_, None, sm_scale, False, block_q, block_k, interpret,
            out_dtype=jnp.float32,
        )

    def future(q, k_, v_):
        b, h, s, _ = q.shape
        return (
            jnp.zeros(q.shape, jnp.float32),
            jnp.full((b, h, s, 1), NEG_INF, jnp.float32),
        )

    return jax.lax.cond(is_past, past, future, q, k_cur, v_cur)


def _lse_combine(m, l, acc, o_b, lse_b):
    """Fold a block's normalized output into the running (max, sum, acc):
    o_b's unnormalized row sum is exp(lse_b), so blocks combine by lse like
    partial softmaxes."""
    m_new = jnp.maximum(m, lse_b)
    alpha = jnp.exp(m - m_new)
    w_b = jnp.exp(lse_b - m_new)
    return m_new, l * alpha + w_b, acc * alpha + o_b * w_b


def _ring_flash_fwd(q, k, v, idx, axis_name, cp, sm_scale, block_q, block_k, interpret):
    """q/k/v local (B, n, S/cp, d); ``idx`` the ring position scalar.
    Returns (out, global lse).

    Hop 0 (the diagonal, locally causal block) runs before the scan; each
    scan step permutes K/V first and then computes, so no hop rotates K/V
    only to discard the result."""
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    b, h, s, d = q.shape

    o0, lse0 = _flash_fwd(
        q, k, v, None, sm_scale, True, block_q, block_k, interpret,
        out_dtype=jnp.float32,
    )
    m0 = jnp.full((b, h, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0, l0, acc0 = _lse_combine(m0, l0, acc0, o0, lse0)

    def step(carry, step_idx):
        k_cur, v_cur, m, l, acc = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        owner = (idx - step_idx) % cp
        o_b, lse_b = _ring_block(
            owner < idx, q, k_cur, v_cur, sm_scale, block_q, block_k, interpret
        )
        m, l, acc = _lse_combine(m, l, acc, o_b, lse_b)
        return (k_cur, v_cur, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(1, cp)
    )
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, idx, axis_name, cp, sm_scale, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd(q, k, v, idx, axis_name, cp, sm_scale, block_q, block_k, interpret)
    return out


def _ring_flash_fwd_rule(q, k, v, idx, axis_name, cp, sm_scale, block_q, block_k, interpret):
    out, lse = _ring_flash_fwd(q, k, v, idx, axis_name, cp, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v, idx, out, lse)


def _ring_flash_bwd_rule(axis_name, cp, sm_scale, block_q, block_k, interpret, res, do):
    """Second ring pass over the flash dq/dkv kernels with the GLOBAL
    lse/delta. Hop 0 (diagonal) runs before the scan; scan steps permute
    first, then compute. dk/dv accumulators ride the ring with their K/V
    block — cp-1 hops inside the scan plus one final hop lands them home."""
    q, k, v, idx, out, lse = res
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    def block_grads(is_past, k_cur, v_cur):
        def past(k_, v_):
            return _flash_bwd_parts(
                q, k_, v_, do, lse, delta, None, sm_scale, False, block_q, block_k,
                interpret,
            )

        def future(k_, v_):
            return jnp.zeros_like(q), jnp.zeros_like(k_), jnp.zeros_like(v_)

        return jax.lax.cond(is_past, past, future, k_cur, v_cur)

    dq0, dk0, dv0 = _flash_bwd_parts(
        q, k, v, do, lse, delta, None, sm_scale, True, block_q, block_k, interpret
    )

    def step(carry, step_idx):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        owner = (idx - step_idx) % cp
        dq_b, dk_b, dv_b = block_grads(owner < idx, k_cur, v_cur)
        dq = dq + dq_b.astype(jnp.float32)
        dk_cur = dk_cur + dk_b.astype(jnp.float32)
        dv_cur = dv_cur + dv_b.astype(jnp.float32)
        return (k_cur, v_cur, dk_cur, dv_cur, dq), None

    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step,
        (k, v, dk0.astype(jnp.float32), dv0.astype(jnp.float32), dq0.astype(jnp.float32)),
        jnp.arange(1, cp),
    )
    dk = jax.lax.ppermute(dk, axis_name, perm)
    dv = jax.lax.ppermute(dv, axis_name, perm)
    didx = np.zeros(idx.shape, dtype=jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), didx


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def _ring_flash_local(q, k, v, idx_arr, axis_name: str, cp: int, sm_scale: float, block: int):
    """shard_map body for the flash path. q/k/v local (B, S/cp, n, d)."""
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _ring_flash(
        qt, kt, vt, idx_arr[0], axis_name, cp, sm_scale, block, block, _use_interpret()
    )
    return jnp.transpose(out, (0, 2, 1, 3))


def _flash_block_size(s_local: int) -> int:
    """Largest power-of-two tile <= 1024 dividing the local sequence; 0 if the
    shape doesn't tile (callers fall back to the einsum ring)."""
    for block in (1024, 512, 256, 128, 64, 32, 16, 8):
        if s_local % block == 0:
            return block
    return 0


def ring_attention(
    q, k, v, mesh: Mesh, cp_axes: Sequence[str], sm_scale: float | None = None,
    batch_axes: Sequence[str] = (), head_axes: Sequence[str] = (),
):
    """q/k/v: (B, S, n, d) global arrays; sequence ring-sharded over cp_axes.

    Uses the Pallas flash kernels per ring hop when the local sequence
    tiles; otherwise the einsum online-softmax fallback. ``batch_axes``/
    ``head_axes``: the layer's dp/tp axes — the batch and head dims keep
    their sharding through the (fully-manual) region instead of being
    gathered."""
    cp = int(np.prod([mesh.shape[a] for a in cp_axes]))
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    axis = tuple(cp_axes)
    b_ax = tuple(batch_axes) or None
    h_ax = tuple(head_axes) or None
    spec = P(b_ax, axis, h_ax, None)
    mesh = ambient_or(mesh)
    block = _flash_block_size(q.shape[1] // cp)
    if block:
        local = functools.partial(
            _ring_flash_local, axis_name=axis, cp=cp, sm_scale=sm_scale, block=block
        )
    else:
        local = functools.partial(
            _ring_attn_local, axis_name=axis, cp=cp, sm_scale=sm_scale
        )
    # ring position fed as a sharded arange rather than lax.axis_index: when
    # this shard_map nests inside the pipeline's manual-'pp' region, shardy
    # cannot lower axis_index (it would re-bind the parent's manual axes),
    # while plain data sharding over the cp axes works — same linearization
    # as ppermute over the axis tuple
    idx_arr = jnp.arange(cp, dtype=jnp.int32)
    fn = compat.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(axis)),
        out_specs=spec,
        axis_names=manual_axis_names(mesh),
        check_vma=False,
    )
    return fn(q, k, v, idx_arr)


def ring_decoder_layer(
    x, p, cfg: ModelConfig, mesh, cp_axes, cos_sin,
    batch_axes: Sequence[str] = (), head_axes: Sequence[str] = (),
):
    """Decoder layer with the attention core ring-parallelized (drop-in for
    modeling.decoder_layer when a layer strategy sets cp > 1)."""

    def attn(xn):
        b, s, h = xn.shape
        hd = cfg.head_dim
        q, k, v = modeling.project_qkv_heads(xn, p["attn"], cfg)
        if cfg.pos_embed == "rope":
            cos, sin = cos_sin
            q = modeling.apply_rope(q, cos, sin)
            k = modeling.apply_rope(k, cos, sin)
        k = modeling._repeat_kv(k, cfg.num_heads // k.shape[2])
        v = modeling._repeat_kv(v, cfg.num_heads // v.shape[2])
        o = modeling._constrain_attn_out(
            ring_attention(
                q, k, v, mesh, cp_axes,
                batch_axes=batch_axes, head_axes=head_axes,
            ),
            cfg,
        )
        return modeling.attn_output(o, p["attn"], cfg, xn.dtype)

    x = x + attn(modeling.norm(x, p["attn_norm"], cfg))
    x = x + modeling.mlp_block(modeling.norm(x, p["mlp_norm"], cfg), p["mlp"], cfg)
    return x
