"""galvatron_tpu — a TPU-native automatic-parallelism training framework.

Re-implements the capabilities of Hetu-Galvatron (reference: /root/reference)
from scratch on JAX/XLA/pjit/Pallas:

- a **search engine** (``galvatron_tpu.search``) that, given profiled hardware
  (ICI/DCN collective bandwidths) and model (per-layer time/memory) data, runs
  a dynamic program choosing a per-layer hybrid parallelism strategy over
  {PP degree, TP degree, TP axis layout, DP vs ZeRO-2/ZeRO-3, sequence
  parallelism, activation rematerialization} under a per-chip HBM budget
  (reference: galvatron/core/search_engine.py, dynamic_programming.py);
- a **runtime** (``galvatron_tpu.parallel``) that executes layer-heterogeneous
  strategies on a single ``jax.sharding.Mesh``: per-layer ``NamedSharding``
  rules replace Megatron TP wrappers, ``with_sharding_constraint`` boundaries
  replace activation redistribution (reference: galvatron/core/redistribute.py),
  parameter/optimizer sharding specs replace FSDP wrapping (reference:
  galvatron/core/parallel.py), and hand-written GPipe / 1F1B schedules over
  ``shard_map``/``ppermute`` replace the NCCL p2p pipeline engine (reference:
  galvatron/core/pipeline/pipeline.py);
- **Pallas kernels** (``galvatron_tpu.ops``) for flash attention, fused
  RMSNorm, and ring attention over ICI (long-context context parallelism);
- **profilers** (``galvatron_tpu.profiling``) measuring ICI collective
  bandwidth per (group size, axis layout) — the nccl-tests equivalent — and
  per-layer compute time / memory via measured steps and XLA memory analysis
  (reference: galvatron/core/profiler.py, galvatron/profile_hardware/);
- a **model zoo** (``galvatron_tpu.models``) of GPT/LLaMA-family decoder
  models in functional JAX.

Unlike the reference, there is no vendored Megatron fork and no torch: the
compute path is pure JAX, and the only native component is the C++ dynamic-
programming search core (csrc/dp_core.cpp equivalent).
"""

__version__ = "0.1.0"

# jax-version compatibility lives in galvatron_tpu.compat (imported by the
# call sites) — the third-party jax namespace is never mutated here.
