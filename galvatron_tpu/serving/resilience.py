"""Serving resilience: request lifecycle state machine + engine crash supervision.

PR 1/7 made the *training* path preemption-proof; this module brings the
serving stack to the same bar. Production continuous-batching systems
(Orca's iteration-level scheduling, vLLM's preemptible slot management)
treat admission, cancellation, and engine recovery as first-class state
transitions — so every request here moves through ONE explicit lifecycle::

    QUEUED → PREFILLING → DECODING → {COMPLETED, FAILED, EXPIRED,
                                      CANCELLED, SHED}

Every transition is a tracer instant (``req_<state>``) and lands in a
counter, so ``/healthz``, ``/metrics`` and the flight recorder all tell the
same story. The terminal states are disjoint by *cause*:

- ``COMPLETED``  — eos or token budget reached; full result delivered.
- ``FAILED``     — prefill/decode exception or engine crash mid-flight
                   (continuous batching cannot replay mid-decode KV state).
- ``EXPIRED``    — out-waited its TTL: in queue (never admitted) or
                   mid-decode (the end-to-end deadline, checked at
                   decode-step granularity; ``deadline_policy`` decides
                   whether the partial text is returned or the request
                   fails).
- ``CANCELLED``  — the client vanished (disconnect poll) or asked to stop;
                   the slot frees at the next decode iteration.
- ``SHED``       — queued-but-unstarted when the server began draining;
                   failed fast so a load balancer retries elsewhere.

:class:`EngineSupervisor` is the in-process analogue of
``core/elastic.py``'s restart decision table: a decode/prefill-loop crash
fails the in-flight requests fast (503 ``engine_restarted``), keeps queued
requests that still have TTL budget, resets the KV cache, warm-rebuilds
the two pinned programs from the PR 9 artifact store, and restarts the
loop under ``core/retry.py`` full-jitter backoff — bounded by
``max_restarts`` *consecutive no-progress* restarts (a completion between
crashes resets the budget, exactly like elastic's committed-step rule).
Every restart lands a flight-recorder dump.
"""

from __future__ import annotations

import time
from typing import Optional

from galvatron_tpu.core.restart_policy import RestartPolicy
from galvatron_tpu.obs.tracing import tracer

# --- request lifecycle states ------------------------------------------------

QUEUED = "QUEUED"
PREFILLING = "PREFILLING"
DECODING = "DECODING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"
EXPIRED = "EXPIRED"
CANCELLED = "CANCELLED"
SHED = "SHED"

#: every lifecycle state, in flow order (DESIGN.md § Serving resilience
#: renders this exact list — a doc-sync test keeps them matched)
STATES = (QUEUED, PREFILLING, DECODING, COMPLETED, FAILED, EXPIRED,
          CANCELLED, SHED)

TERMINAL = frozenset((COMPLETED, FAILED, EXPIRED, CANCELLED, SHED))

#: legal transitions. QUEUED can reach every terminal state (expiry/shed/
#: cancel/failure all happen pre-admission too); a zero-token request
#: completes straight from QUEUED. PREFILLING cannot COMPLETE (the first
#: sampled token only exists once the request is DECODING).
TRANSITIONS = {
    QUEUED: frozenset((PREFILLING, COMPLETED, FAILED, EXPIRED, CANCELLED, SHED)),
    PREFILLING: frozenset((DECODING, FAILED, EXPIRED, CANCELLED)),
    DECODING: frozenset((COMPLETED, FAILED, EXPIRED, CANCELLED)),
}

#: terminal state → scheduler counter bumped on entry (the non-terminal
#: states are counted by admission itself: submitted/admitted)
_STATE_COUNTER = {
    COMPLETED: "completed",
    FAILED: "failed",
    EXPIRED: "expired",
    CANCELLED: "cancelled",
    SHED: "shed",
}


class IllegalTransition(RuntimeError):
    """A lifecycle edge outside :data:`TRANSITIONS` — a scheduling bug."""


def advance(req, state: str, counters=None, **info) -> None:
    """Move ``req`` to ``state``: validate the edge, record a tracer
    instant, and bump the matching terminal counter. ``info`` lands on the
    tracer instant (reason, detail, ...)."""
    cur = getattr(req, "state", QUEUED)
    if state not in TRANSITIONS.get(cur, frozenset()):
        raise IllegalTransition(
            f"request {req.rid}: illegal lifecycle transition {cur} → {state}"
        )
    req.state = state
    trace_id = getattr(req, "trace_id", None)
    if trace_id is not None:
        # the fleet-minted correlation id (obs/correlate.py) rides every
        # lifecycle instant so the merged timeline links this process's
        # events to the router's dispatch spans
        tracer.instant(f"req_{state.lower()}", rid=req.rid,
                       trace_id=trace_id, **info)
    else:
        tracer.instant(f"req_{state.lower()}", rid=req.rid, **info)
    if counters is not None:
        name = _STATE_COUNTER.get(state)
        if name:
            counters.inc(name)
        if state == CANCELLED and info.get("reason") == "disconnect":
            counters.inc("cancelled_disconnect")
        if state == EXPIRED and cur == DECODING:
            counters.inc("expired_decode")


# --- exceptions the server maps to HTTP --------------------------------------


class RequestShed(RuntimeError):
    """Queued-but-unstarted when the drain began — 503, retry elsewhere."""


class RequestCancelled(RuntimeError):
    """Cancelled before completion (client disconnect); nobody is listening."""


class DeadlineExceeded(RuntimeError):
    """The end-to-end deadline passed mid-decode and ``deadline_policy`` is
    ``fail`` (``partial`` resolves the future with the truncated text
    instead)."""


class EngineDraining(RuntimeError):
    """The server is draining: admission is closed. Mapped to 503 with a
    ``Retry-After`` header so a well-behaved client backs off."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class EngineClosed(RuntimeError):
    """The engine is shut down (or gave up restarting): ``submit`` refuses
    immediately instead of returning a future that can never resolve."""


class EngineRestarted(RuntimeError):
    """The engine crashed and restarted while this request was in flight.
    Mid-decode KV state cannot be replayed — the request fails fast with a
    503 so the client retries against the recovered engine.
    ``retry_after_s`` is the supervisor's own backoff delay (it knows when
    the engine will be looping again) — the server surfaces it as a
    ``Retry-After`` header, like draining 503s, and the fleet router uses
    it to time the re-dispatch."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# --- in-process crash supervision -------------------------------------------


class EngineSupervisor:
    """Restart decision table for the serving engine, in-process.

    Modeled on ``core/elastic.py``'s supervisor, minus the child process:
    the engine loop thread survives the crash, so "restart" means fail the
    unreplayable in-flight work, reset the KV cache, warm-rebuild the two
    pinned programs, and keep looping. Decisions mirror elastic's:

    ====================================  =====================================
    condition                             decision
    ====================================  =====================================
    crash, completions since last crash   restart (budget resets — progress)
    crash, no progress, budget left       restart after full-jitter backoff
    crash, no progress, budget exhausted  give up: engine closes, /readyz
                                          unready, every request 503s
    ====================================  =====================================

    Every crash lands a flight-recorder dump (when ``flight_dir`` is set)
    and a tracer instant; restarts count into ``engine_restarts``.
    """

    def __init__(self, max_restarts: int = 3, backoff_s: float = 0.05,
                 backoff_cap_s: float = 2.0, flight_dir: Optional[str] = None):
        self.max_restarts = max(0, int(max_restarts))
        # the shared supervisor decision table (core/restart_policy.py):
        # elastic, this supervisor, and the fleet router all budget restarts
        # with the same consecutive-no-progress arithmetic
        self.policy = RestartPolicy(
            max_restarts=self.max_restarts,
            backoff_s=float(backoff_s),
            backoff_cap_s=float(backoff_cap_s),
        )
        self.flight_dir = flight_dir
        self.restarts_total = 0
        self.gave_up = False
        self._last_completed = 0

    @property
    def consecutive(self) -> int:
        """Restarts since the last completed request (the policy's streak)."""
        return self.policy.consecutive

    def note_counter_reset(self) -> None:
        """The engine reset its counters (``reset_metrics``): drop the
        completed-count high-water mark with them, so progress detection
        keeps comparing like with like."""
        self._last_completed = 0

    def on_crash(self, engine, exc: BaseException) -> bool:
        """One crash of the engine loop. Returns True when the loop should
        continue (recovered), False on give-up (the engine is dead)."""
        completed = engine.scheduler.counters.get("completed")
        progressed = completed > self._last_completed
        self._last_completed = completed
        decision = self.policy.on_failure(progressed)
        tracer.instant(
            "engine_crash", error=f"{type(exc).__name__}: {exc}",
            consecutive=decision.consecutive, in_flight=len(engine._by_slot),
        )
        # in-flight 503s carry the supervisor's own backoff as Retry-After:
        # the engine is looping again after exactly that delay (give-up 503s
        # carry none — there is nothing to come back to)
        engine._crash_cleanup(
            exc,
            retry_after_s=None if decision.give_up else decision.backoff_s,
        )
        if self.flight_dir:
            from galvatron_tpu.obs.flight import dump_flight

            dump_flight(
                self.flight_dir, tracer,
                reason=f"engine {'give-up' if decision.give_up else 'crash'}: "
                       f"{type(exc).__name__}: {exc}",
                extra={"restarts_total": self.restarts_total,
                       "consecutive": decision.consecutive},
            )
        if decision.give_up:
            self.gave_up = True
            tracer.instant("engine_give_up", restarts=self.restarts_total,
                           consecutive=decision.consecutive)
            return False
        self.restarts_total += 1
        engine.counters.inc("engine_restarts")
        if decision.backoff_s:
            time.sleep(decision.backoff_s)
        engine._warm_rebuild()
        tracer.instant("engine_restart", restarts=self.restarts_total,
                       backoff_s=round(decision.backoff_s, 3))
        return True
