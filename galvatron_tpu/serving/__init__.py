"""Continuous-batching serving subsystem.

Three layers, one per module:

- [[kv_slots]] ``SlotKVCache`` — persistent fixed-shape device KV cache,
  host-side slot allocator (per-slot offset/length, alloc/free/reset).
- [[scheduler]] ``Scheduler`` — FIFO admission queue with per-request TTL,
  bounded depth (``QueueFull``), expiry (``RequestExpired``), counters.
- [[engine]] ``Engine`` — the loop: one jitted decode step over all slots
  per iteration, chunked prefill on admission, host-side per-request
  sampling, retire-on-eos/budget.

``server.GenerationService`` submits into the engine via futures; the
legacy serialized ``generate_np`` path remains available when the engine is
disabled (``--num_slots 0``).
"""

from galvatron_tpu.serving.engine import Engine
from galvatron_tpu.serving.kv_slots import SlotKVCache
from galvatron_tpu.serving.scheduler import (
    QueueFull,
    Request,
    RequestExpired,
    Scheduler,
)

__all__ = [
    "Engine",
    "SlotKVCache",
    "Scheduler",
    "Request",
    "QueueFull",
    "RequestExpired",
]
