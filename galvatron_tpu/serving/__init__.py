"""Continuous-batching serving subsystem.

Four layers, one per module:

- [[kv_slots]] ``SlotKVCache`` — persistent fixed-shape device KV cache,
  host-side slot allocator (per-slot offset/length, alloc/free/reset,
  invariant ``audit``).
- [[paged_kv]] ``PagedKVCache`` — block-granular alternative backend
  (``--kv_num_blocks``): fixed device block pool + per-request block
  tables, refcounted copy-on-write prefix sharing keyed by token-chunk
  hash, LRU eviction of cold prefix blocks, block-headroom admission
  (``NoFreeBlocks`` is its can't-happen-in-the-engine exhaustion error).
- [[scheduler]] ``Scheduler`` — FIFO admission queue with per-request TTL,
  bounded depth (``QueueFull``), expiry (``RequestExpired``), shed-on-drain,
  counters.
- [[resilience]] — the request lifecycle state machine (QUEUED →
  PREFILLING → DECODING → {COMPLETED, FAILED, EXPIRED, CANCELLED, SHED}),
  the in-process ``EngineSupervisor`` crash-restart decision table, and the
  exceptions the server maps to HTTP (``EngineDraining``/``EngineClosed``/
  ``EngineRestarted``/``RequestShed``/``RequestCancelled``/
  ``DeadlineExceeded``).
- [[engine]] ``Engine`` — the loop: one jitted decode step over all slots
  per iteration, chunked prefill on admission, host-side per-request
  sampling, retire-on-eos/budget/deadline/cancel, graceful ``drain`` with
  a post-drain zero-leak ``audit``.  Optional numerics/speed levers:
  per-channel int8 weights (``--serve_quant int8``, ops/quant.py) and
  speculative decoding with the [[speculative]] prompt-lookup drafter
  (``--spec_decode_k``) — both program-key terms the AOT warmup must see.
- [[speculative]] ``PromptLookupDrafter`` — checkpoint-free n-gram
  drafter + the exactness contract for draft verification (greedy output
  is bit-identical to plain decode; sampling keeps the distribution via
  rejection sampling).
- [[fleet]] ``FleetRouter`` — the horizontal layer (``cli serve-fleet``):
  N engine replicas behind one router with health-driven dispatch
  (STARTING → READY → DRAINING → DEAD), mid-flight failover inside the
  end-to-end deadline, supervised replica restarts, and rolling drain.
  Imported lazily (it spawns subprocesses; most serving users never
  need it): ``from galvatron_tpu.serving.fleet import FleetRouter``.

``server.GenerationService`` submits into the engine via futures; the
legacy serialized ``generate_np`` path remains available when the engine is
disabled (``--num_slots 0``).
"""

from galvatron_tpu.serving.engine import Engine
from galvatron_tpu.serving.kv_slots import SlotKVCache
from galvatron_tpu.serving.paged_kv import NoFreeBlocks, PagedKVCache
from galvatron_tpu.serving.resilience import (
    DeadlineExceeded,
    EngineClosed,
    EngineDraining,
    EngineRestarted,
    EngineSupervisor,
    RequestCancelled,
    RequestShed,
)
from galvatron_tpu.serving.scheduler import (
    QueueFull,
    Request,
    RequestExpired,
    Scheduler,
)
from galvatron_tpu.serving.speculative import PromptLookupDrafter, make_drafter

__all__ = [
    "Engine",
    "PromptLookupDrafter",
    "make_drafter",
    "SlotKVCache",
    "PagedKVCache",
    "NoFreeBlocks",
    "Scheduler",
    "Request",
    "QueueFull",
    "RequestExpired",
    "RequestShed",
    "RequestCancelled",
    "DeadlineExceeded",
    "EngineDraining",
    "EngineClosed",
    "EngineRestarted",
    "EngineSupervisor",
]
