"""Paged KV cache: block pool + COW prefix sharing for the serving engine.

vLLM's PagedAttention decouples KV memory from worst-case sequence length by
carving the cache into fixed-size blocks and giving every request a *block
table* instead of a contiguous slot row. This module rebuilds that design
TPU-natively: the device side is ONE fixed-shape pool ``(L, num_blocks,
block_size, kv_heads, head_dim)`` plus a static ``(num_slots, max_blocks)``
int32 table threaded through the jitted step as a regular traced operand —
so, unlike vLLM's CUDA path which reallocates per-sequence page lists, every
compiled program here sees the same shapes forever and the engine keeps its
pinned-program-count discipline (see DESIGN.md § Paged KV cache).

Host side, :class:`PagedKVCache` is a block allocator layered on the same
slot bookkeeping as :class:`~galvatron_tpu.serving.kv_slots.SlotKVCache`:

* every non-null block is in exactly one of three states —

  - FREE:   on the free list, contents dead;
  - OWNED:  ``refcount >= 1``, referenced by one or more request tables;
  - CACHED: ``refcount == 0`` but registered in the prefix registry, kept
    warm for reuse and evictable in LRU order;

* block 0 is the reserved *null block*: table padding beyond a request's
  reserved capacity points at it, writes of prompt-padding garbage land in
  it, and causal masking guarantees it is never attended;

* prefix sharing is block-granular and keyed by a *cumulative* token-chunk
  hash (hash of the parent chunk's hash plus this block's tokens), so a
  match at chunk ``i`` proves the entire prefix ``[0, (i+1)*block_size)``
  is identical. A shared system prompt is prefilled once; later requests
  attach the matching blocks read-only (refcount bump) and re-prefill only
  the tail. The first write into a shared or registered block copies it
  first (copy-on-write via one tiny jitted device program).

Blocks are never zeroed on reuse for the same reason slots aren't: a new
owner writes before anything can read, and the causal mask hides every
position at or beyond a row's own write offset.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from galvatron_tpu.analysis.locks import make_rlock
from galvatron_tpu.models import generation
from galvatron_tpu.models.modeling import ModelConfig

from .kv_slots import effective_max_seq_len

NULL_BLOCK = 0

# every non-null block is in exactly one of these states (audit() checks
# the partition); DESIGN.md § Paged KV cache renders the transition table
# and a doc-sync test keeps the two from drifting
BLOCK_STATES = ("FREE", "OWNED", "CACHED")


class NoFreeBlocks(RuntimeError):
    """Block pool exhausted: nothing on the free list and no refcount-0
    prefix block left to evict. Admission must gate on ``can_admit`` so
    this is never raised mid-decode."""


@partial(jax.jit, donate_argnames=("k", "v"))
def _copy_block(k, v, src, dst):
    """Device-side COW copy of one pool block (both k and v planes, all
    layers). ``src``/``dst`` are traced int32 scalars so this stays one
    compiled program for the lifetime of the pool."""
    return k.at[:, dst].set(k[:, src]), v.at[:, dst].set(v[:, src])


def _chunk_hash(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


def prefix_hashes(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Cumulative hash per *full* block-sized chunk of ``tokens``."""
    out: List[bytes] = []
    parent = b"galvatron-prefix-root"
    for i in range(len(tokens) // block_size):
        parent = _chunk_hash(parent, tokens[i * block_size : (i + 1) * block_size])
        out.append(parent)
    return out


class PagedKVCache:
    """Fixed device block pool + host block allocator with COW prefix cache.

    Drop-in replacement for :class:`SlotKVCache` at the engine boundary:
    the slot-level API (``alloc``/``free``/``fits``/``audit``/``lengths``)
    is identical, with block bookkeeping layered underneath. ``num_blocks``
    counts pool rows *including* the reserved null block; ``num_blocks=-1``
    sizes the pool to the same HBM footprint as the equivalent slot cache
    (``num_slots * max_blocks`` usable blocks).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        num_slots: int,
        block_size: int = 16,
        num_blocks: int = -1,
        max_seq_len: Optional[int] = None,
        prefix_cache: bool = True,
    ):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {block_size}")
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_seq_len = effective_max_seq_len(cfg, max_seq_len)
        self.max_blocks = -(-self.max_seq_len // self.block_size)  # ceil
        if num_blocks == -1:
            num_blocks = self.num_slots * self.max_blocks + 1
        self.num_blocks = int(num_blocks)
        if self.num_blocks - 1 < self.max_blocks:
            raise ValueError(
                f"kv_num_blocks={self.num_blocks} cannot hold one max-length "
                f"request ({self.max_blocks} blocks + 1 null block)"
            )
        self.prefix_cache_enabled = bool(prefix_cache)

        # allocator bookkeeping lock: the engine loop owns the device pool
        # and the per-slot arrays (lengths/tables/pool), but allocator state
        # is read from handler threads (stats/can_admit) while the loop
        # mutates it — an RLock because public methods nest (fork → alloc,
        # append → reserve → _append_block)
        self._lock = make_rlock("paged_kv")

        # device pool: (L, num_blocks, block_size, kv_heads, head_dim) —
        # same layout as a slot cache with batch=num_blocks, len=block_size
        self.pool = generation.init_kv_cache(cfg, self.num_blocks, self.block_size)

        # slot bookkeeping (mirrors SlotKVCache exactly)
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self._free_slots: List[int] = list(range(self.num_slots - 1, -1, -1))  # guarded-by: self._lock
        self._active: set = set()  # guarded-by: self._lock

        # block bookkeeping
        self.tables = np.zeros((self.num_slots, self.max_blocks), np.int32)
        self._refcount = np.zeros((self.num_blocks,), np.int32)  # guarded-by: self._lock
        self._free_blocks: List[int] = list(range(self.num_blocks - 1, 0, -1))  # guarded-by: self._lock
        self._slot_blocks: Dict[int, List[int]] = {}  # guarded-by: self._lock

        # prefix cache: chunk hash -> block, block -> chunk hash, plus an
        # LRU over CACHED (refcount-0, registered) blocks only
        self._registry: Dict[bytes, int] = {}  # guarded-by: self._lock
        self._block_hash: Dict[int, bytes] = {}  # guarded-by: self._lock
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # guarded-by: self._lock

        # cumulative counters (survive reset — they are lifetime totals)
        self.prefix_hits = 0  # guarded-by: self._lock
        self.prefix_misses = 0  # guarded-by: self._lock
        self.prefix_evictions = 0  # guarded-by: self._lock
        self.cow_copies = 0  # guarded-by: self._lock

    # -- slot allocator (SlotKVCache-compatible surface) ---------------------

    def alloc(self) -> Optional[int]:
        """Claim a free slot with an empty block table; None when occupied."""
        with self._lock:
            if not self._free_slots:
                return None
            slot = self._free_slots.pop()
            self._active.add(slot)
            self.lengths[slot] = 0
            self.tables[slot, :] = NULL_BLOCK
            self._slot_blocks[slot] = []
            return slot

    def free(self, slot: int) -> None:
        """Release a slot and drop one reference from each of its blocks.
        Blocks reaching refcount 0 return to the free list, unless they are
        registered prefix blocks — those become CACHED (LRU-evictable)."""
        with self._lock:
            if slot not in self._active:
                raise ValueError(f"slot {slot} is not active")
            for b in self._slot_blocks.pop(slot):
                self._unref(b)
            self._active.discard(slot)
            self.lengths[slot] = 0
            self.tables[slot, :] = NULL_BLOCK
            self._free_slots.append(slot)

    def reset(self) -> None:
        """Release everything and reallocate the device pool (engine crash
        recovery / drain). The jitted steps DONATE the pool buffers, so
        after a step that died mid-call a fresh pool is the only safe
        state; the prefix registry is cleared with it — its blocks' device
        contents are gone."""
        with self._lock:
            self._active.clear()
            self.lengths[:] = 0
            self._free_slots = list(range(self.num_slots - 1, -1, -1))
            self.tables[:] = NULL_BLOCK
            self._refcount[:] = 0
            self._free_blocks = list(range(self.num_blocks - 1, 0, -1))
            self._slot_blocks = {}
            self._registry.clear()
            self._block_hash.clear()
            self._lru.clear()
            self.pool = generation.init_kv_cache(self.cfg, self.num_blocks, self.block_size)

    # -- views ---------------------------------------------------------------

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free_slots)

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def active_slots(self) -> List[int]:
        with self._lock:
            return sorted(self._active)

    @property
    def occupancy(self) -> float:
        with self._lock:
            return len(self._active) / self.num_slots

    @property
    def blocks_total(self) -> int:
        """Usable blocks (the null block is not allocatable)."""
        return self.num_blocks - 1

    @property
    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free_blocks)

    @property
    def blocks_cached(self) -> int:
        with self._lock:
            return len(self._lru)

    @property
    def blocks_active(self) -> int:
        with self._lock:
            return self.blocks_total - len(self._free_blocks) - len(self._lru)

    def blocks_held(self, slot: int) -> int:
        with self._lock:
            return len(self._slot_blocks.get(slot, ()))

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Same per-request capacity bound as the slot cache."""
        return prompt_len >= 1 and prompt_len + max_new_tokens <= self.max_seq_len

    # -- block allocator core ------------------------------------------------

    def _take_block(self) -> int:  # holds: self._lock
        """Pop a free block, evicting the least-recently-used CACHED prefix
        block if the free list is dry. Raises NoFreeBlocks when neither
        source has a block — admission gating makes that unreachable in the
        engine."""
        if self._free_blocks:
            return self._free_blocks.pop()
        if self._lru:
            b, _ = self._lru.popitem(last=False)
            h = self._block_hash.pop(b)
            del self._registry[h]
            self.prefix_evictions += 1
            return b
        raise NoFreeBlocks(
            f"block pool exhausted ({self.blocks_total} blocks, 0 free, 0 evictable)"
        )

    def _unref(self, b: int) -> None:  # holds: self._lock
        if self._refcount[b] <= 0:
            raise ValueError(f"block {b} refcount underflow")
        self._refcount[b] -= 1
        if self._refcount[b] == 0:
            if b in self._block_hash:
                self._lru[b] = None  # OWNED -> CACHED (most recently used)
            else:
                self._free_blocks.append(b)  # OWNED -> FREE

    def _claim_cached(self, b: int) -> None:  # holds: self._lock
        """CACHED -> OWNED: first re-attachment of a refcount-0 registered
        block pulls it out of the eviction queue."""
        if self._refcount[b] == 0:
            del self._lru[b]
        self._refcount[b] += 1

    def _append_block(self, slot: int) -> None:  # holds: self._lock
        blocks = self._slot_blocks[slot]
        if len(blocks) >= self.max_blocks:
            raise ValueError(f"slot {slot} already holds max_blocks={self.max_blocks}")
        b = self._take_block()
        self._refcount[b] += 1
        self.tables[slot, len(blocks)] = b
        blocks.append(b)

    def reserve(self, slot: int, upto_len: int) -> None:
        """Extend the slot's table to cover positions ``[0, upto_len)``.
        The engine reserves a request's WORST-CASE footprint (prompt +
        max_new_tokens) at admission so decode never allocates and can
        never fail on pool pressure mid-request."""
        need = -(-int(upto_len) // self.block_size)
        with self._lock:
            while len(self._slot_blocks[slot]) < need:
                self._append_block(slot)

    def ensure_writable(self, slot: int, lo: int, hi: int) -> None:
        """Copy-on-write guard for a pending write to positions ``[lo, hi)``:
        any covered block that is shared (refcount > 1) or registered in the
        prefix cache is replaced by a private copy first, so the write can
        never corrupt another request's context or a cached prefix."""
        if hi <= lo:
            return
        with self._lock:
            blocks = self._slot_blocks[slot]
            first = lo // self.block_size
            last = min(-(-hi // self.block_size), len(blocks))
            for i in range(first, last):
                b = blocks[i]
                if self._refcount[b] == 1 and b not in self._block_hash:
                    continue  # sole un-registered owner: write in place
                nb = self._take_block()
                self.pool = generation.KVCache(
                    *_copy_block(self.pool.k, self.pool.v, np.int32(b), np.int32(nb))
                )
                self._refcount[nb] = 1
                self._unref(b)
                blocks[i] = nb
                self.tables[slot, i] = nb
                self.cow_copies += 1

    def append(self, slot: int, n: int = 1) -> None:
        """Advance a slot by ``n`` positions, allocating and COW-protecting
        blocks as needed (allocator-level surface for tests/fuzzing; the
        engine reserves worst-case up front instead)."""
        with self._lock:
            lo = int(self.lengths[slot])
            hi = lo + int(n)
            if hi > self.max_seq_len:
                raise ValueError(f"slot {slot} overflow: {hi} > {self.max_seq_len}")
            self.reserve(slot, hi)
            self.ensure_writable(slot, lo, hi)
            self.lengths[slot] = hi

    def fork(self, src: int) -> Optional[int]:
        """Clone a slot by reference: the new slot shares every block of
        ``src`` (refcount bump, zero copies); the first divergent write on
        either side triggers COW. None when no slot is free."""
        with self._lock:
            if src not in self._active:
                raise ValueError(f"slot {src} is not active")
            slot = self.alloc()
            if slot is None:
                return None
            for b in self._slot_blocks[src]:
                self._refcount[b] += 1
            self._slot_blocks[slot] = list(self._slot_blocks[src])
            self.tables[slot, :] = self.tables[src, :]
            self.lengths[slot] = self.lengths[src]
            return slot

    # -- prefix cache --------------------------------------------------------

    def _match_len(self, tokens: Sequence[int]) -> int:  # holds: self._lock
        """Longest registered prefix of ``tokens`` in full blocks, capped so
        at least one prompt token is always re-prefilled (the engine needs
        the request's own last-position logits to sample the first token)."""
        if not self.prefix_cache_enabled:
            return 0
        cap = (len(tokens) - 1) // self.block_size
        matched = 0
        for h in prefix_hashes(tokens[: cap * self.block_size], self.block_size):
            if h not in self._registry:
                break
            matched += 1
        return matched

    def attach_prefix(self, slot: int, tokens: Sequence[int]) -> int:
        """Attach the longest cached prefix of ``tokens`` to ``slot`` as
        read-only shared blocks. Returns the matched length in tokens (a
        multiple of block_size); the engine prefills from there."""
        if not self.prefix_cache_enabled:
            return 0
        with self._lock:
            cap = (len(tokens) - 1) // self.block_size
            matched = self._match_len(tokens)
            blocks = self._slot_blocks[slot]
            if blocks:
                raise ValueError(f"slot {slot} already holds blocks; attach first")
            hashes = prefix_hashes(tokens[: matched * self.block_size], self.block_size)
            for i, h in enumerate(hashes):
                b = self._registry[h]
                self._claim_cached(b)
                self.tables[slot, i] = b
                blocks.append(b)
            self.prefix_hits += matched
            self.prefix_misses += cap - matched
            return matched * self.block_size

    def register_prefix(self, slot: int, tokens: Sequence[int]) -> int:
        """Publish the slot's full prompt blocks into the prefix registry
        (idempotent; chunks already registered — including ones this slot
        attached — are skipped). Called once, right after prefill, so
        sharing starts while the donor is still decoding. Returns the
        number of newly registered blocks.

        Every FULL prompt block registers (``len // block_size`` of them —
        unlike matching, which caps at ``(len-1) // block_size`` so one
        token always re-prefills): full blocks are never written again —
        decode appends at ``len`` and beyond, which lands in later blocks."""
        if not self.prefix_cache_enabled:
            return 0
        with self._lock:
            cap = len(tokens) // self.block_size
            blocks = self._slot_blocks[slot]
            added = 0
            for i, h in enumerate(prefix_hashes(tokens[: cap * self.block_size], self.block_size)):
                if h in self._registry:
                    continue
                b = blocks[i]
                if b in self._block_hash:
                    continue  # block already backs a different registered chunk
                self._registry[h] = b
                self._block_hash[b] = h
                added += 1
            return added

    # -- admission gate ------------------------------------------------------

    def cow_overlap_blocks(self, matched_len: int, prompt_len: int, chunk: int) -> int:
        """Blocks the prefill window can dirty *below* the attached prefix:
        the engine slides its last fixed-size window left to stay inside
        capacity, and when ``max_seq_len - chunk < matched_len`` that window
        re-writes shared positions, forcing COW copies that need spare
        blocks. (Recomputed k/v is bit-identical, so correctness is never
        at stake — only block accounting.)"""
        lo = self.max_seq_len - chunk
        if prompt_len + chunk <= self.max_seq_len or lo >= matched_len:
            return 0
        return -(-matched_len // self.block_size) - lo // self.block_size

    def can_admit(self, tokens: Sequence[int], max_new_tokens: int, chunk: int = 0) -> bool:
        """True when the pool has headroom (free + evictable) for this
        request's worst-case footprint after prefix sharing. This is what
        the engine's admission gate consults, so shed/queue decisions see
        real block headroom instead of slot count."""
        prompt_len = len(tokens)
        if not self.fits(prompt_len, max_new_tokens):
            return False
        with self._lock:
            matched = self._match_len(tokens)
            need = -(-(prompt_len + max_new_tokens) // self.block_size) - matched
            need += self.cow_overlap_blocks(matched * self.block_size, prompt_len, chunk)
            return need <= len(self._free_blocks) + len(self._lru)

    # -- audit ---------------------------------------------------------------

    def audit(self) -> dict:
        """Allocator invariant check, extending the SlotKVCache partition
        audit to blocks: every non-null block is FREE xor OWNED xor CACHED,
        refcounts equal the number of slot tables referencing each block,
        and registry/LRU bookkeeping is bijective."""
        with self._lock:
            free_set = set(self._free_slots)
            slots_ok = (
                len(free_set) == len(self._free_slots)
                and not (free_set & self._active)
                and (free_set | self._active) == set(range(self.num_slots))
            )

            free_blocks = set(self._free_blocks)
            owned = {b for b in range(1, self.num_blocks) if self._refcount[b] > 0}
            cached = set(self._lru)
            refs = np.zeros((self.num_blocks,), np.int32)
            for blocks in self._slot_blocks.values():
                for b in blocks:
                    refs[b] += 1
            blocks_ok = (
                len(free_blocks) == len(self._free_blocks)  # no duplicate frees
                and NULL_BLOCK not in free_blocks | owned | cached
                and not (free_blocks & owned)
                and not (free_blocks & cached)
                and not (owned & cached)
                and (free_blocks | owned | cached) == set(range(1, self.num_blocks))
                and bool(np.all(self._refcount >= 0))
                and bool(np.all(refs == self._refcount))
                and set(self._registry.values()) == set(self._block_hash)
                and all(self._registry[h] == b for b, h in self._block_hash.items())
                and cached == {b for b in self._block_hash if self._refcount[b] == 0}
                and set(self._slot_blocks) == self._active
            )
            return {
                "ok": slots_ok and blocks_ok,
                "free": len(self._free_slots),
                "active": len(self._active),
                "num_slots": self.num_slots,
                "blocks_ok": blocks_ok,
                "blocks_total": self.blocks_total,
                "blocks_free": len(self._free_blocks),
                "blocks_cached": len(self._lru),
                "blocks_active": self.blocks_total - len(self._free_blocks) - len(self._lru),
            }

    def block_stats(self) -> dict:
        with self._lock:
            return {
                "kv_block_size": self.block_size,
                "kv_blocks_total": self.blocks_total,
                "kv_blocks_free": len(self._free_blocks),
                "kv_blocks_cached": len(self._lru),
                "kv_blocks_active": self.blocks_total - len(self._free_blocks) - len(self._lru),
                "prefix_cache_enabled": self.prefix_cache_enabled,
                "prefix_cache_hits": self.prefix_hits,
                "prefix_cache_misses": self.prefix_misses,
                "prefix_cache_evictions": self.prefix_evictions,
                "cow_copies": self.cow_copies,
            }
