"""Slot-managed persistent KV cache for the continuous-batching engine.

One fixed-shape device cache — ``(L, num_slots, max_seq_len, kv_heads,
head_dim)`` k and v — lives for the whole server lifetime; requests borrow a
*slot* (one batch row) for their duration and return it on retirement
(vLLM's PagedAttention manages blocks within a sequence; here the unit is
the whole-sequence slot, which is what maps onto JAX's static-shape jit:
every decode step sees the same array shapes, so the compiled program is
reused forever — no per-request allocation, no recompiles).

Host side this class is a tiny allocator: a free list plus per-slot
offset/length bookkeeping. Device side it owns the ``KVCache`` pytree that
the engine threads through its jitted prefill/decode calls. Slots are NOT
zeroed on reuse — a new request's prefill writes positions ``[0, P)`` before
any query can see them, and causal masking hides every position beyond a
row's own write offset, so stale keys from the previous occupant are never
attended.
"""

from __future__ import annotations

import warnings
from typing import List, Optional

import numpy as np

from galvatron_tpu.analysis.locks import make_lock
from galvatron_tpu.models import generation
from galvatron_tpu.models.modeling import ModelConfig


def effective_max_seq_len(cfg: ModelConfig, max_seq_len: Optional[int]) -> int:
    """Clamp a caller-requested per-request capacity to the model's trained
    ``cfg.max_seq_len`` — rope tables and position embeddings don't extend
    past it. A request above the model bound used to be clamped *silently*,
    which made ``--max_seq_len 8192`` on a 2k model look honoured while every
    long request was rejected at admission; now the mismatch warns and the
    effective value is surfaced through ``Engine.stats()`` → /healthz."""
    if max_seq_len is None:
        return int(cfg.max_seq_len)
    requested = int(max_seq_len)
    if requested > cfg.max_seq_len:
        warnings.warn(
            f"requested max_seq_len={requested} exceeds model cfg.max_seq_len="
            f"{cfg.max_seq_len}; clamping — the replica serves at most "
            f"{cfg.max_seq_len} tokens per request (see max_seq_len_effective "
            "in /healthz)",
            RuntimeWarning,
            stacklevel=3,
        )
        return int(cfg.max_seq_len)
    return requested


class SlotKVCache:
    """Fixed ``(num_slots, max_seq_len)`` KV cache + slot allocator."""

    def __init__(self, cfg: ModelConfig, num_slots: int, max_seq_len: Optional[int] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.max_seq_len = effective_max_seq_len(cfg, max_seq_len)
        # device arrays; reassigned by the engine after every jitted step
        self.cache = generation.init_kv_cache(cfg, self.num_slots, self.max_seq_len)
        # host bookkeeping: length = tokens materialized in the slot so far
        # (prompt + generated); the next token lands at position == length.
        # The allocator lock covers the free list + active set: the engine
        # loop allocates/frees while handler threads read the occupancy
        # views through stats()/healthz
        self._lock = make_lock("kv_slots")
        self.lengths = np.zeros((self.num_slots,), np.int32)
        self._free: List[int] = list(range(self.num_slots - 1, -1, -1))  # guarded-by: self._lock
        self._active: set = set()  # guarded-by: self._lock

    # -- allocator ----------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Claim a free slot (length reset to 0); None when fully occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._active.add(slot)
            self.lengths[slot] = 0
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot not in self._active:
                raise ValueError(f"slot {slot} is not active")
            self._active.discard(slot)
            self.lengths[slot] = 0
            self._free.append(slot)

    def reset(self) -> None:
        """Release every slot and reallocate the device cache (engine
        failure recovery / drain). The engine's jitted steps DONATE the
        cache buffers — after a step that died mid-call the old arrays may
        already be invalidated, so a fresh cache is the only safe state."""
        with self._lock:
            self._active.clear()
            self.lengths[:] = 0
            self._free = list(range(self.num_slots - 1, -1, -1))
            self.cache = generation.init_kv_cache(self.cfg, self.num_slots, self.max_seq_len)

    # -- views --------------------------------------------------------------

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def active_slots(self) -> List[int]:
        with self._lock:
            return sorted(self._active)

    @property
    def occupancy(self) -> float:
        with self._lock:
            return len(self._active) / self.num_slots

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Whole lifetime of the request stays inside the slot: the last
        generated token sits at position prompt_len + max_new_tokens - 1."""
        return prompt_len >= 1 and prompt_len + max_new_tokens <= self.max_seq_len

    def audit(self) -> dict:
        """Allocator invariant check (the drain/chaos harness's zero-leak
        proof): the free list and the active set partition the slot range
        exactly — no double-frees, no leaks, no phantom slots."""
        with self._lock:
            free_set = set(self._free)
            ok = (
                len(free_set) == len(self._free)          # no duplicate frees
                and not (free_set & self._active)         # disjoint
                and (free_set | self._active) == set(range(self.num_slots))
            )
            return {
                "ok": ok,
                "free": len(self._free),
                "active": len(self._active),
                "num_slots": self.num_slots,
            }
