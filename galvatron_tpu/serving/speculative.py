"""Speculative decoding: checkpoint-free drafting + exact verification.

Speculative decoding (Leviathan et al. 2023) turns decode's bandwidth
bound into arithmetic: a cheap drafter proposes ``k`` tokens, the target
model scores all of them in ONE forward at ``(B, 1+k)`` — re-reading the
weight set once instead of ``1+k`` times — and rejection sampling keeps
the output distribution exactly the target's. Acceptance is the whole
game: ``accepted_tokens_per_step`` > 1 is pure decode speedup, ≈1 is pure
overhead.

The drafter here is PROMPT LOOKUP (n-gram continuation): propose the
tokens that followed the longest matching suffix n-gram earlier in the
request's own prompt+generation. No draft checkpoint, no second model, no
extra HBM — and it is strong exactly where serving traffic is repetitive
(RAG quoting its context, code completion, structured output), weak on
free prose (acceptance → 0, the engine falls back to plain decode steps).

Exactness contract (what the tests pin):

- A proposed token ``d`` is a point-mass draft distribution ``q = δ_d``.
  Rejection sampling accepts ``d`` with probability ``p(d)`` under the
  target's processed distribution (same temperature/top-k/top-p pipeline
  as the engine's host sampler); on rejection the replacement token is
  drawn from the residual ``norm(p - p(d)·δ_d)`` — i.e. ``p`` with ``d``
  struck out and renormalized, which the engine realizes by writing
  ``-inf`` into the stored logits at ``d``.
- Under greedy (temperature ≤ 0) this degenerates to "accept while
  ``argmax == d``", and striking out a non-argmax token cannot move the
  argmax — so greedy speculative output is BIT-IDENTICAL to the plain
  engine, not merely close.
"""

from __future__ import annotations

from typing import List, Sequence


class PromptLookupDrafter:
    """Longest-suffix n-gram lookup over the request's own token stream.

    For ``n`` from ``ngram_max`` down to ``ngram_min``: find the most
    recent earlier occurrence of the sequence's last ``n`` tokens and
    propose (up to ``k``) tokens that followed it. First hit wins — longer
    matches are better predictors. Returns ``[]`` when nothing matches;
    the engine then runs a plain decode step for free (no wasted verify).
    """

    name = "prompt_lookup"

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1,
                 max_scan: int = 4096):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{ngram_min}, {ngram_max}]"
            )
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        # bound the suffix scan: O(max_scan · ngram_max) per draft keeps the
        # host-side cost flat for book-length sessions
        self.max_scan = int(max_scan)

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        if k <= 0:
            return []
        toks = list(tokens[-self.max_scan:])
        ln = len(toks)
        for n in range(min(self.ngram_max, ln - 1), self.ngram_min - 1, -1):
            suffix = toks[ln - n:]
            # scan right-to-left: recency beats earlier occurrences
            for i in range(ln - n - 1, -1, -1):
                if toks[i:i + n] == suffix:
                    cont = toks[i + n:i + n + k]
                    if cont:
                        return [int(t) for t in cont]
        return []


_DRAFTERS = {"prompt_lookup": PromptLookupDrafter}


def make_drafter(name: str, **kw):
    """Drafter registry: ``--spec_drafter`` values resolve here (a future
    draft-model drafter registers alongside without touching the engine)."""
    try:
        cls = _DRAFTERS[name]
    except KeyError:
        raise ValueError(
            f"unknown spec drafter {name!r}; available: "
            f"{sorted(_DRAFTERS)}"
        ) from None
    return cls(**kw)
