"""Serving fleet: a resilient multi-replica router (`cli serve-fleet`).

One engine process is one blast radius: a single crash or deploy takes 100%
of capacity with it. This module fronts N engine replicas — each a real
``cli serve`` subprocess on its own port, warm-started from a shared
``--compile_cache_dir`` — behind one router that owns four concerns:

**Health-driven dispatch.** Every replica moves through a small in-router
state machine, probed via its own ``/healthz``/``/readyz``::

    STARTING → READY → DRAINING → DEAD
                                    ↓ (supervised respawn)
                                 STARTING

``/readyz`` stays 503 (status ``starting``) until the replica's engine has
warm-started AND served a first real generation (`server.py` readiness
gating), so the router never dispatches into a replica still paying cold
compile. Dispatch picks the least-loaded READY replica by live occupancy —
router-side outstanding requests plus the replica's last-probed queue
depth — with optional session affinity (a stable hash of the request's
``session`` key, falling back to least-loaded when the pinned replica is
out). Fleet-wide admission is one shared bounded gate: saturation returns
ONE coherent 503 (``detail: fleet_saturated``) with a ``Retry-After``
header instead of N replicas' inconsistent ``queue_full``s.

**Failover.** The router records every request at admission (exact body +
absolute deadline), so a request whose replica dies mid-flight — process
kill, connection reset, or a well-formed 503 ``engine_restarted`` from the
replica's own crash supervision — is re-dispatched to a sibling with the
*remaining* end-to-end deadline (``ttl_s`` is rewritten per attempt) and
``retried_from`` counted into the response. The per-request retry budget
(``--retry_budget``) bounds the cascade: a poison request that kills every
replica it touches fails after the budget instead of felling the fleet.

**Replica supervision.** A crashed replica restarts under the same
consecutive-no-progress / full-jitter decision table as ``run-elastic``
and the in-process ``EngineSupervisor`` (`core/restart_policy.py` — one
shared policy module): progress = completions in the dead incarnation;
give-up marks the replica permanently DEAD and the fleet *degrades* to the
remaining capacity rather than dying. Respawned replicas warm from the
shared compile-artifact store, so recovery costs manifest hits.

**Rolling drain.** ``POST /drain?rolling=1`` drains replicas one at a
time through the per-replica drain (PR 10's zero-downtime sequence) while
the rest keep serving — each drained process exits 0 and is respawned
(waiting for READY) before the next begins, which is the zero-downtime
deploy: during the whole roll the fleet keeps admitting and every admitted
request is served (work shed by the draining replica re-dispatches to a
sibling). Plain ``POST /drain`` (and SIGTERM) is the fleet *shutdown*:
router admission closes, replicas drain sequentially (so siblings absorb
shed work until the last one), and a fleet-level post-drain audit checks
every replica exited 0, reported ``leaked=False``, and left a flight dump.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Set

from galvatron_tpu.analysis.locks import make_lock
from galvatron_tpu.core import faults
from galvatron_tpu.core.restart_policy import RestartPolicy
from galvatron_tpu.obs.tracing import tracer
from galvatron_tpu.utils.metrics import Counters

# --- replica lifecycle states ------------------------------------------------

STARTING = "STARTING"
READY = "READY"
DRAINING = "DRAINING"
DEAD = "DEAD"

#: every replica state, in flow order (DESIGN.md § Serving fleet renders
#: this exact list — a doc-sync test keeps them matched)
REPLICA_STATES = (STARTING, READY, DRAINING, DEAD)

#: legal edges. STARTING can die (crash before ever ready) or be told to
#: drain (a rolling drain reaching a mid-restart replica); DEAD → STARTING
#: is the supervised respawn.
REPLICA_TRANSITIONS = {
    STARTING: frozenset((READY, DRAINING, DEAD)),
    READY: frozenset((DRAINING, DEAD)),
    DRAINING: frozenset((DEAD,)),
    DEAD: frozenset((STARTING,)),
}


class IllegalReplicaTransition(RuntimeError):
    """A replica-state edge outside :data:`REPLICA_TRANSITIONS` — a router
    bookkeeping bug, never a replica's fault."""


_LISTEN_RE = re.compile(r"listening on http://[^:]+:(\d+)/api")

#: fleet-only CLI flags (each takes one value) stripped from the raw serve
#: argv before it is forwarded to replicas — everything else (model shape,
#: engine knobs, --compile_cache_dir) forwards verbatim, so a replica is
#: exactly the `cli serve` the same command line would have started.
FLEET_ONLY_FLAGS = frozenset((
    "--replicas", "--replica_ports", "--retry_budget", "--fleet_max_pending",
    "--max_replica_restarts", "--replica_restart_backoff_s",
    "--probe_interval_s", "--session_affinity", "--rolling_drain",
    "--fleet_dir", "--replica_faults",
))

#: router-owned flags also stripped (the router binds --port/--host itself;
#: --flight_dir is re-pointed per replica so dumps do not collide)
_ROUTER_OWNED_FLAGS = frozenset(("--port", "--host", "--flight_dir"))


def replica_argv(serve_argv: Sequence[str], port: int,
                 flight_dir: str) -> List[str]:
    """The raw ``serve-fleet`` argv minus fleet/router-owned flags, plus
    this replica's own ``--port``/``--flight_dir``. Handles both
    ``--flag value`` and ``--flag=value`` spellings."""
    strip = FLEET_ONLY_FLAGS | _ROUTER_OWNED_FLAGS
    out: List[str] = []
    i = 0
    argv = list(serve_argv)
    while i < len(argv):
        tok = argv[i]
        flag = tok.split("=", 1)[0]
        if flag in strip:
            i += 1 if "=" in tok else 2
            continue
        out.append(tok)
        i += 1
    out += ["--port", str(port), "--host", "127.0.0.1",
            "--flight_dir", flight_dir]
    return out


class Replica:
    """One supervised engine subprocess, as the router sees it."""

    def __init__(self, idx: int, serve_argv: Sequence[str], *,
                 fleet_dir: str, port: int = 0,
                 env: Optional[Dict[str, str]] = None,
                 restart_policy: Optional[RestartPolicy] = None):
        self.idx = idx
        self.serve_argv = list(serve_argv)
        self.fixed_port = int(port)  # 0 = ephemeral, parsed from stdout
        self.port: Optional[int] = None
        self.flight_dir = os.path.join(fleet_dir, f"replica-{idx}", "flight")
        self.log_path = os.path.join(fleet_dir, f"replica-{idx}.log")
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self._state_lock = make_lock("replica.state")
        self._state = DEAD  # guarded-by: self._state_lock — spawn() advances DEAD → STARTING
        self.reachable = False
        self.last_health: Dict[str, Any] = {}
        self._lock = make_lock("replica.dispatch")
        self._outstanding = 0  # guarded-by: self._lock — router-side in-flight dispatches
        self.policy = restart_policy or RestartPolicy()
        self._restarts_total = 0  # guarded-by: self._lock
        self.gave_up = False
        self.last_exit_code: Optional[int] = None
        self._spawn_lock = make_lock("replica.spawn")

    # -- state machine ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._state_lock:
            return self._state

    @state.setter
    def state(self, value: str) -> None:
        # raw assignment, no transition validation — the pre-property API
        # (tests/harnesses force lifecycle states); real transitions go
        # through advance()
        with self._state_lock:
            self._state = value

    def advance(self, state: str, **info) -> None:
        """Validated state transition. Same-state advances are no-ops: the
        monitor and a drain can both observe the same exit — DEAD twice is
        one fact seen from two threads, not a bookkeeping bug."""
        with self._state_lock:
            if state == self._state:
                return
            if state not in REPLICA_TRANSITIONS.get(self._state, frozenset()):
                raise IllegalReplicaTransition(
                    f"replica {self.idx}: illegal transition "
                    f"{self._state} → {state}"
                )
            self._state = state
        tracer.instant(f"replica_{state.lower()}", idx=self.idx,
                       port=self.port, **info)

    def try_advance(self, state: str, only_from, **info) -> bool:
        """Atomic conditional transition: advance to ``state`` only if the
        current state is in ``only_from``, under the state lock. The probe
        and drain threads both move replicas concurrently with the exit
        observer — a check-then-advance outside the lock would raise
        :class:`IllegalReplicaTransition` on perfectly legal races (a
        replica dying between the check and the advance)."""
        with self._state_lock:
            if self._state not in only_from:
                return False
            self._state = state
        tracer.instant(f"replica_{state.lower()}", idx=self.idx,
                       port=self.port, **info)
        return True

    # -- process control ----------------------------------------------------

    def spawn(self) -> bool:
        """Launch (or relaunch) the ``cli serve`` subprocess; returns False
        when another thread already respawned this replica (the monitor's
        crash respawn and a rolling drain's deploy respawn can race — the
        spawn lock makes exactly ONE incarnation win, never an orphaned
        process only one of them tracks). stdout is teed into the
        per-replica log (the drain audit greps it) and the listening line
        is parsed for the port when it is ephemeral."""
        with self._spawn_lock:
            if self.state != DEAD:
                return False
            os.makedirs(self.flight_dir, exist_ok=True)
            self.reachable = False
            self.last_health = {}
            self.port = self.fixed_port or None
            argv = replica_argv(self.serve_argv, self.fixed_port,
                                self.flight_dir)
            from galvatron_tpu.core.elastic import child_pythonpath_env

            self.proc = subprocess.Popen(
                [sys.executable, "-m", "galvatron_tpu.cli", "serve", *argv],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=child_pythonpath_env(self.env if self.env is not None
                                         else dict(os.environ)),
            )
            self.last_exit_code = None
            self.advance(STARTING, pid=self.proc.pid)
        threading.Thread(
            target=self._pump_stdout, args=(self.proc,),
            name=f"replica-{self.idx}-log", daemon=True,
        ).start()
        return True

    def _pump_stdout(self, proc: subprocess.Popen) -> None:
        """Drain the child's stdout into the log file (a full pipe would
        wedge the replica mid-print) and latch the listening port."""
        with open(self.log_path, "a") as log:
            for line in proc.stdout:
                log.write(line)
                log.flush()
                # latch the port only for the CURRENT incarnation: a stale
                # pump still draining a dead process's buffer must not
                # publish the dead port over the respawn's fresh one
                if self.port is None and self.proc is proc:
                    m = _LISTEN_RE.search(line)
                    if m:
                        self.port = int(m.group(1))

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def kill(self) -> None:
        if self.alive:
            self.proc.kill()

    # -- dispatch bookkeeping ----------------------------------------------

    def begin_dispatch(self) -> None:
        with self._lock:
            self._outstanding += 1

    def end_dispatch(self) -> None:
        with self._lock:
            self._outstanding -= 1

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    @outstanding.setter
    def outstanding(self, value: int) -> None:
        # pre-property API (tests seed load levels); the dispatch path uses
        # begin_dispatch/end_dispatch
        with self._lock:
            self._outstanding = value

    @property
    def restarts_total(self) -> int:
        with self._lock:
            return self._restarts_total

    def note_restart(self) -> int:
        """Count one respawn of this replica. Serialized under the dispatch
        lock: the monitor's crash respawn and a rolling drain's deploy
        respawn run on different threads, and the former bare ``+= 1`` on
        both sides could lose an increment (read-modify-write race). Returns
        the new total (callers log it)."""
        with self._lock:
            self._restarts_total += 1
            return self._restarts_total

    @property
    def load(self) -> float:
        """Live occupancy the dispatcher minimizes: router-side outstanding
        plus the replica's last-probed queue depth + active slots."""
        s = (self.last_health.get("serving") or {})
        return (self.outstanding
                + float(s.get("queue_depth") or 0)
                + float(s.get("active_slots") or 0))

    @property
    def completed(self) -> int:
        """Completions of the CURRENT incarnation (counters reset with the
        process) — the supervision progress signal."""
        return int((self.last_health.get("serving") or {}).get("completed") or 0)

    def dispatchable(self) -> bool:
        return (self.state == READY and self.reachable and self.alive
                and self.port is not None)

    def snapshot(self) -> Dict[str, Any]:
        s = (self.last_health.get("serving") or {})
        return {
            "idx": self.idx,
            "port": self.port,
            "pid": self.pid,
            "state": self.state,
            "reachable": self.reachable,
            "outstanding": self.outstanding,
            "queue_depth": s.get("queue_depth"),
            "active_slots": s.get("active_slots"),
            "completed": s.get("completed"),
            "engine_restarts": s.get("engine_restarts"),
            "ttft_p99_s": s.get("ttft_p99_s"),
            "restarts": self.restarts_total,
            "gave_up": self.gave_up,
            "last_exit_code": self.last_exit_code,
        }


class _FleetGate:
    """Fleet-wide bounded admission (shared backpressure): one semaphore in
    front of every replica, so saturation is ONE coherent 503."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._sem = threading.BoundedSemaphore(self.capacity)
        self._lock = make_lock("fleet.gate")
        self.in_use = 0  # guarded-by: self._lock

    def acquire(self) -> bool:
        ok = self._sem.acquire(blocking=False)
        if ok:
            with self._lock:
                self.in_use += 1
        return ok

    def release(self) -> None:
        with self._lock:
            self.in_use -= 1
        self._sem.release()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"capacity": self.capacity, "in_use": self.in_use,
                    "saturated": self.in_use >= self.capacity}


class FleetRouter:
    """Router process state: replicas, monitors, dispatch, drain."""

    def __init__(self, serve_argv: Sequence[str], *,
                 replicas: int = 2,
                 replica_ports: Optional[Sequence[int]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 retry_budget: int = 2,
                 request_ttl_s: Optional[float] = 30.0,
                 drain_timeout_s: float = 30.0,
                 max_replica_restarts: int = 3,
                 restart_backoff_s: float = 0.5,
                 restart_backoff_cap_s: float = 10.0,
                 probe_interval_s: float = 0.25,
                 session_affinity: bool = False,
                 fleet_max_pending: int = 0,
                 fleet_dir: Optional[str] = None,
                 replica_env: Optional[Dict[str, str]] = None,
                 replica_faults: str = "",
                 rolling_shutdown: bool = True,
                 num_slots_hint: int = 4,
                 startup_timeout_s: float = 180.0):
        n = max(1, int(replicas))
        ports = list(replica_ports or [])
        if ports and len(ports) != n:
            raise ValueError(
                f"--replica_ports names {len(ports)} ports for "
                f"--replicas {n}"
            )
        self.host = host
        self.retry_budget = max(0, int(retry_budget))
        self.request_ttl_s = request_ttl_s if (request_ttl_s or 0) > 0 else None
        self.drain_timeout_s = float(drain_timeout_s)
        self.probe_interval_s = max(0.02, float(probe_interval_s))
        self.session_affinity = bool(session_affinity)
        self.rolling_shutdown = bool(rolling_shutdown)
        self.startup_timeout_s = float(startup_timeout_s)
        self.fleet_dir = fleet_dir or os.path.abspath("fleet_dir")
        os.makedirs(self.fleet_dir, exist_ok=True)
        # replica env: the router's own GALVATRON_FAULTS must NOT leak into
        # replicas (router-level chaos like kill_replica_at_dispatch would
        # otherwise arm nonsense keys in every child); --replica_faults is
        # the explicit way to degrade the replicas themselves
        env = dict(replica_env if replica_env is not None else os.environ)
        env.pop(faults.ENV_VAR, None)
        if replica_faults:
            env[faults.ENV_VAR] = replica_faults
        self.replicas: List[Replica] = [
            Replica(
                i, serve_argv, fleet_dir=self.fleet_dir,
                port=ports[i] if ports else 0, env=env,
                restart_policy=RestartPolicy(
                    max_restarts=max_replica_restarts,
                    backoff_s=restart_backoff_s,
                    backoff_cap_s=restart_backoff_cap_s,
                ),
            )
            for i in range(n)
        ]
        self.gate = _FleetGate(
            fleet_max_pending or n * max(1, int(num_slots_hint)) * 4
        )
        self.counters = Counters(
            "dispatched", "served", "retried", "rejected_saturated",
            "rejected_unready", "rejected_draining", "expired", "failed",
            "client_error", "replica_restarts",
        )
        self.started_at = time.time()
        # SLO burn-rate engine (obs/slo.py), armed by serve_fleet_main; the
        # router observes availability + deadline misses from its dispatch
        # outcomes (TTFT is a replica-side observation — each replica runs
        # its own engine and /healthz unions their degraded_reasons here)
        self.slo = None
        self.draining = False
        self._drain_lock = make_lock("fleet.drain")
        self._rolling_lock = make_lock("fleet.rolling")
        self.drain_audit: Dict[str, Any] = {}
        self._drained = threading.Event()
        self._stop = False
        self._serving = False  # serve_forever started (start() sets it)
        self._monitors: List[threading.Thread] = []
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self.httpd.server_address[1]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        for r in self.replicas:
            r.spawn()
        for r in self.replicas:
            t = threading.Thread(target=self._monitor, args=(r,),
                                 name=f"fleet-monitor-{r.idx}", daemon=True)
            t.start()
            self._monitors.append(t)
        self._serving = True
        threading.Thread(target=self.httpd.serve_forever,
                         name="fleet-http", daemon=True).start()
        return self

    def wait_ready(self, min_replicas: int = 1,
                   timeout_s: Optional[float] = None) -> bool:
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.startup_timeout_s)
        while time.monotonic() < deadline:
            if self.ready_count() >= min_replicas:
                return True
            time.sleep(0.05)
        return False

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.dispatchable())

    @property
    def ready(self) -> bool:
        """Router readiness: at least one dispatchable replica and not
        draining — a degraded fleet is still a fleet."""
        return not self.draining and self.ready_count() > 0

    def close(self) -> None:
        """Hard stop (tests/error paths): kill everything, no drain."""
        self._stop = True
        for r in self.replicas:
            r.kill()
        try:
            if self._serving:
                # shutdown() handshakes with serve_forever — calling it on
                # a never-started server parks forever on the rendezvous
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:  # noqa: BLE001 — already closed is fine
            pass

    # -- replica supervision ------------------------------------------------

    def _monitor(self, r: Replica) -> None:
        """Per-replica monitor: classify exits, probe health, keep the
        state machine honest. The one writer of ``r.state`` outside
        drain()'s DRAINING mark."""
        while not self._stop:
            if r.gave_up:
                time.sleep(self.probe_interval_s)
                continue
            # pin the incarnation: rolling_drain respawns concurrently, and
            # classifying the OLD proc's exit against the NEW proc's state
            # would mark a healthy respawn dead (and leak its process)
            proc = r.proc
            rc = proc.poll() if proc is not None else None
            if rc is not None and r.state != DEAD:
                if r.proc is not proc:
                    continue  # already respawned by another thread
                r.last_exit_code = rc
                r.reachable = False
                expected = r.state == DRAINING or self.draining or self._stop
                r.advance(DEAD, exit_code=rc, expected=expected)
                if expected:
                    continue
                # crash: the shared decision table. Progress = completions
                # in the incarnation that just died BEYOND the startup
                # readiness probe (cli serve's warm generation completes one
                # request per incarnation — counting it would make every
                # post-READY crash look progressed and the give-up budget
                # unreachable)
                progressed = r.completed > 1
                decision = r.policy.on_failure(progressed)
                tracer.instant(
                    "replica_crash", idx=r.idx, exit_code=rc,
                    consecutive=decision.consecutive, progressed=progressed,
                )
                if decision.give_up:
                    r.gave_up = True
                    tracer.instant("replica_give_up", idx=r.idx,
                                   restarts=r.restarts_total)
                    print(f"fleet: replica {r.idx} gave up after "
                          f"{r.restarts_total} restart(s); serving degrades "
                          f"to {self.ready_count()} ready replica(s)",
                          flush=True)
                    continue
                if decision.backoff_s:
                    time.sleep(decision.backoff_s)
                if self._stop or self.draining:
                    continue
                # spawn() is atomic under the replica's spawn lock and only
                # proceeds from DEAD — a rolling drain's deploy respawn
                # racing this crash respawn yields exactly one incarnation
                if r.spawn():
                    n_restarts = r.note_restart()
                    self.counters.inc("replica_restarts")
                    print(f"fleet: replica {r.idx} crashed (exit {rc}); "
                          f"restart {n_restarts} after "
                          f"{decision.backoff_s:.2f}s backoff", flush=True)
                continue
            if rc is None and r.port is not None:
                self._probe(r)
            time.sleep(self.probe_interval_s)

    def _probe(self, r: Replica) -> None:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{r.port}/healthz", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
        except Exception:  # noqa: BLE001 — unreachable is a state, not an error
            r.reachable = False
            return
        r.last_health = doc
        r.reachable = True
        status = doc.get("status")
        ready = bool(doc.get("ready"))
        # try_advance, not advance: a drain/exit can move the replica
        # between our state read and the transition — a lost race here is
        # a no-op, never an IllegalReplicaTransition that kills the monitor
        if r.state == STARTING and ready:
            r.try_advance(READY, (STARTING,))
        elif r.state == READY and status == "draining":
            # an externally-initiated replica drain (operator hit the
            # replica's own /drain): honor it — stop dispatching
            r.try_advance(DRAINING, (READY,), reason="external")
        elif r.state == READY and status == "ok" and not ready:
            # process alive but its engine gave up (crash budget spent):
            # capacity-wise this replica is dead — recycle the process so
            # the supervised respawn gets a fresh engine
            tracer.instant("replica_engine_dead", idx=r.idx)
            r.kill()

    # -- dispatch -----------------------------------------------------------

    def _pick(self, body: Dict[str, Any],
              excluded: Set[int]) -> Optional[Replica]:
        ready = [r for r in self.replicas
                 if r.dispatchable() and r.idx not in excluded]
        if not ready:
            return None
        if self.session_affinity:
            session = body.get("session")
            if isinstance(session, str) and session:
                pinned = self.replicas[
                    zlib.crc32(session.encode()) % len(self.replicas)
                ]
                if pinned in ready:
                    return pinned
        return min(ready, key=lambda r: (r.load, r.idx))

    def handle_api(self, raw: bytes):
        """One routed request: admission record → gate → dispatch loop with
        failover. Returns ``(status_code, payload_dict, headers_or_None)``."""
        if self.draining:
            self.counters.inc("rejected_draining")
            return 503, {"error": "fleet draining", "detail": "draining"}, {
                "Retry-After": str(max(1, int(self.drain_timeout_s)))}
        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            # admission-time request record: the exact body plus an absolute
            # deadline — what makes a mid-flight retry exact (same prompt
            # and params, only ttl_s rewritten to the REMAINING budget)
            ttl = body.get("ttl_s")
            ttl = float(ttl) if ttl is not None else self.request_ttl_s
        except (ValueError, TypeError) as e:
            # a client typo (ttl_s: "abc") is a 400, not a router failure —
            # counted so the outcome partition stays lossless
            self.counters.inc("client_error")
            return 400, {"error": str(e)}, None
        deadline = time.monotonic() + ttl if ttl and ttl > 0 else None
        if not self.gate.acquire():
            self.counters.inc("rejected_saturated")
            return 503, {
                "error": f"fleet saturated "
                         f"({self.gate.capacity} pending requests)",
                "detail": "fleet_saturated",
            }, {"Retry-After": "1"}
        try:
            return self._dispatch_loop(body, deadline)
        finally:
            self.gate.release()

    def _dispatch_loop(self, body: Dict[str, Any], deadline: Optional[float]):
        # distributed tracing (obs/correlate.py): the router is where a
        # request's fleet-wide story starts, so the trace id is minted HERE
        # — and ONLY when tracing is armed. Tracing off ⇒ no id, no header,
        # no clock reads (the replica-side zero-host-sync pin covers this).
        trace_id = None
        if tracer.enabled:
            from galvatron_tpu.obs.correlate import mint_trace_id

            trace_id = mint_trace_id()
            with tracer.span("fleet_request", trace_id=trace_id) as sp:
                return self._dispatch_impl(body, deadline, trace_id, sp)
        return self._dispatch_impl(body, deadline, None, None)

    def _dispatch_impl(self, body: Dict[str, Any], deadline: Optional[float],
                       trace_id: Optional[str], sp):
        attempts = 0  # re-dispatches so far (retried_from in the response)
        excluded: Set[int] = set()
        last_err = None
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.counters.inc("expired")
                    self._slo_observe("deadline_miss_ratio", bad=True)
                    if sp is not None:
                        sp.set(outcome="expired", attempts=attempts)
                    return 503, {
                        "error": "end-to-end deadline exhausted "
                                 f"(after {attempts} retr"
                                 f"{'y' if attempts == 1 else 'ies'})",
                        "detail": "expired",
                    }, None
            r = self._pick(body, excluded)
            if r is None and excluded:
                # every sibling was tried or is out: one more pass over the
                # full fleet (the failed replica may have recovered)
                excluded = set()
                r = self._pick(body, excluded)
            if r is None:
                self.counters.inc("rejected_unready")
                code, payload, headers = 503, {
                    "error": "no ready replica", "detail": "no_ready_replica",
                }, {"Retry-After": "1"}
                if last_err is not None:
                    payload["last_error"] = last_err
                return code, payload, headers
            # inc() returns the post-increment value atomically: two
            # concurrent dispatches must never observe the same index (the
            # kill fault is consumed exactly once)
            n = self.counters.inc("dispatched") - 1
            if faults.kill_replica(n):
                # the chaos seam: SIGKILL the chosen replica shortly after
                # the request lands on it — this very request must fail
                # over to a sibling inside its remaining deadline
                threading.Thread(
                    target=lambda: (time.sleep(0.2), r.kill()),
                    name="fleet-chaos-kill", daemon=True,
                ).start()
            ok, result = self._proxy(r, body, remaining, trace_id=trace_id)
            if ok:
                code, payload, headers = result
                if code == 200 and isinstance(payload, dict):
                    self.counters.inc("served")
                    self._slo_observe("availability", bad=False)
                    self._slo_observe("deadline_miss_ratio", bad=False)
                    if sp is not None:
                        sp.set(outcome="served", replica=r.idx,
                               attempts=attempts)
                    payload["retried_from"] = attempts
                    return code, payload, headers
                detail = payload.get("detail") if isinstance(payload, dict) else None
                if code == 503 and detail in (
                    "engine_restarted", "queue_full", "shed", "draining",
                    "engine_closed",
                ):
                    # the replica refused or lost the request for a reason a
                    # sibling can absorb — failover-eligible
                    last_err = f"replica {r.idx}: 503 {detail}"
                else:
                    # deterministic outcomes (400s, expired, 500s) pass
                    # through verbatim: retrying a poison request elsewhere
                    # is exactly the cascade the budget exists to prevent
                    if detail == "expired":
                        self.counters.inc("expired")
                        self._slo_observe("deadline_miss_ratio", bad=True)
                    elif code >= 500:
                        self.counters.inc("failed")
                        self._slo_observe("availability", bad=True,
                                          detail=str(detail))
                    elif code >= 400:
                        # replica-side validation rejections (bad prompts,
                        # out-of-range budgets): part of the partition too
                        self.counters.inc("client_error")
                    if isinstance(payload, dict) and attempts:
                        payload["retried_from"] = attempts
                    return code, payload, headers
            else:
                # transport-level loss: connection refused/reset/timeout —
                # the replica died (or is dying) with our request on it
                last_err = f"replica {r.idx}: {result}"
                r.reachable = False
            if attempts >= self.retry_budget:
                self.counters.inc("failed")
                self._slo_observe("availability", bad=True,
                                  detail="retry_budget_exhausted")
                if sp is not None:
                    sp.set(outcome="retry_budget_exhausted",
                           attempts=attempts)
                return 503, {
                    "error": f"request failed after {attempts + 1} "
                             f"dispatch(es): {last_err}",
                    "detail": "retry_budget_exhausted",
                }, None
            attempts += 1
            excluded.add(r.idx)
            self.counters.inc("retried")
            if trace_id is not None:
                # the failover hop carries the request's trace id so the
                # merged timeline shows the router handing THIS request from
                # the dead replica to its sibling
                tracer.instant("fleet_failover", replica=r.idx,
                               attempts=attempts, trace_id=trace_id,
                               error=str(last_err)[:200])
            else:
                tracer.instant("fleet_failover", replica=r.idx,
                               attempts=attempts, error=str(last_err)[:200])

    def _slo_observe(self, rule: str, bad: bool, **info) -> None:
        """One router-level SLO sample (obs/slo.py); no-op when no SLO
        engine is armed."""
        if self.slo is not None:
            self.slo.observe(rule, bad=bad, **info)

    def _proxy(self, r: Replica, body: Dict[str, Any],
               remaining: Optional[float],
               trace_id: Optional[str] = None):
        """Forward one attempt to one replica. Returns ``(True, (code,
        payload, headers))`` for any HTTP response, ``(False, error_str)``
        for transport-level loss. ``trace_id`` (tracing armed only) rides
        the X-Galvatron-Trace-Id header so the replica's spans and
        lifecycle instants join this request's fleet-wide trace."""
        fwd = dict(body)
        fwd.pop("session", None)  # router-level concern, not the engine's
        if remaining is not None:
            fwd["ttl_s"] = max(0.05, remaining)
        data = json.dumps(fwd).encode()
        timeout = (remaining + 10.0) if remaining is not None else 600.0
        hdrs = {"Content-Type": "application/json"}
        if trace_id is not None:
            from galvatron_tpu.obs.correlate import TRACE_HEADER

            hdrs[TRACE_HEADER] = trace_id
        req = urllib.request.Request(
            f"http://127.0.0.1:{r.port}/api", data=data,
            headers=hdrs, method="POST",
        )
        r.begin_dispatch()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return True, (resp.status, json.loads(resp.read()), None)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {"error": "unparseable replica response"}
            headers = None
            ra = e.headers.get("Retry-After") if e.headers else None
            if ra:
                headers = {"Retry-After": ra}
            return True, (e.code, payload, headers)
        except Exception as e:  # noqa: BLE001 — transport loss is an outcome
            return False, f"{type(e).__name__}: {e}"
        finally:
            r.end_dispatch()

    # -- drain --------------------------------------------------------------

    def _drain_one(self, r: Replica, timeout_s: float) -> Dict[str, Any]:
        """PR 10's per-replica drain, driven from the router: mark DRAINING
        (dispatch stops), POST /drain, wait for exit, audit the exit code,
        the drained log line, and the flight dump."""
        # try_advance: the replica may die between the state read and the
        # mark — it then drains via its exit path, which is fine
        r.try_advance(DRAINING, (STARTING, READY), reason="fleet")
        proc = r.proc  # pin the incarnation: a racing respawn must not
        # swap the handle out from under the wait
        posted = False
        if r.alive and r.port is not None:
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{r.port}/drain", data=b"",
                    method="POST",
                ), timeout=10)
                posted = True
            except Exception:  # noqa: BLE001 — a dying replica still drains via exit
                pass
        rc = None
        if proc is not None:
            if not posted and proc.poll() is None:
                # no reachable /drain (mid-respawn, port unknown): SIGTERM
                # runs the replica's OWN graceful drain — a SIGKILL here
                # would fail a healthy replica's audit for no reason
                proc.terminate()
            try:
                rc = proc.wait(timeout=timeout_s + 15.0)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    rc = proc.wait(timeout=timeout_s + 15.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    rc = proc.wait(timeout=10)
        r.last_exit_code = rc
        if r.state != DEAD:
            r.advance(DEAD, exit_code=rc, expected=True)
        return self._audit_one(r, rc)

    def _audit_one(self, r: Replica, rc: Optional[int]) -> Dict[str, Any]:
        try:
            log = open(r.log_path).read()
        except OSError:
            log = ""
        clean = "server drained: leaked=False" in log
        dumps = (os.listdir(r.flight_dir)
                 if os.path.isdir(r.flight_dir) else [])
        flight = any(f.startswith("flight_") for f in dumps)
        return {
            "idx": r.idx, "exit_code": rc, "clean_drain": clean,
            "flight_dump": flight, "restarts": r.restarts_total,
            "ok": rc == 0 and clean and flight,
        }

    def rolling_drain(self) -> Dict[str, Any]:
        """Zero-downtime deploy: drain each replica in turn (the rest keep
        serving — router admission stays OPEN), audit its exit, respawn it,
        wait for READY, then move to the next. Serialized: two concurrent
        rolls would drain the fleet from both ends — but a roll takes
        minutes, so a second request must NOT park its handler thread on
        the lock for that long (the GTL203 class: a roll blocks on
        ``proc.wait`` and readiness sleeps while holding it). The losing
        caller gets an immediate ``in_progress`` report instead."""
        if not self._rolling_lock.acquire(blocking=False):
            return {"rolling": False, "in_progress": True, "ok": False,
                    "error": "a rolling drain is already running"}
        try:
            audits = []
            for r in self.replicas:
                if r.gave_up:
                    audits.append({"idx": r.idx, "skipped": "gave_up"})
                    continue
                # a mid-restart replica finishes starting before its turn
                deadline = time.monotonic() + self.startup_timeout_s
                while (r.state == STARTING and time.monotonic() < deadline
                       and not self._stop):
                    time.sleep(0.05)
                audit = self._drain_one(r, self.drain_timeout_s)
                audits.append(audit)
                if self._stop or self.draining:
                    break  # a fleet shutdown raced the roll: stop respawning
                if r.spawn():
                    r.note_restart()
                    self.counters.inc("replica_restarts")
                    r.policy.reset()  # a deploy is a fresh incarnation, not a crash
                # else: the monitor's crash respawn won the race — either
                # way exactly one incarnation is coming up; wait for it
                if not self._wait_replica_ready(r):
                    audits[-1]["respawn_ready"] = False
                else:
                    audits[-1]["respawn_ready"] = True
            out = {
                "rolling": True,
                "replicas": audits,
                "ok": all(a.get("ok") and a.get("respawn_ready", True)
                          for a in audits if "skipped" not in a),
            }
            tracer.instant("fleet_rolling_drain_done", ok=out["ok"])
            print(f"fleet rolling drain: ok={out['ok']} "
                  f"audit={json.dumps(out)}", flush=True)
            return out
        finally:
            self._rolling_lock.release()

    def _wait_replica_ready(self, r: Replica) -> bool:
        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline and not self._stop:
            if r.dispatchable():
                return True
            time.sleep(0.05)
        return False

    def drain(self, reason: str = "drain") -> Dict[str, Any]:
        """Fleet shutdown: admission closes (one coherent 503 +
        Retry-After; /readyz unready), replicas drain sequentially — work
        shed by a draining replica re-dispatches to the still-open
        siblings until the last one — then the router stops. Idempotent;
        returns the fleet-level post-drain audit."""
        with self._drain_lock:
            first = not self.draining
            self.draining = True
        if not first:
            self._drained.wait(
                timeout=(self.drain_timeout_s + 20.0) * len(self.replicas)
            )
            return self.drain_audit
        tracer.instant("fleet_drain_begin", reason=reason)
        audits = []
        targets = [r for r in self.replicas if not r.gave_up]
        if self.rolling_shutdown:
            for r in targets:
                audits.append(self._drain_one(r, self.drain_timeout_s))
        else:
            threads = []
            results: Dict[int, Dict[str, Any]] = {}

            def one(rep):
                results[rep.idx] = self._drain_one(rep, self.drain_timeout_s)

            for r in targets:
                t = threading.Thread(target=one, args=(r,), daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=self.drain_timeout_s + 30.0)
            audits = [results.get(r.idx, {"idx": r.idx, "ok": False})
                      for r in targets]
        self._stop = True
        audit = {
            "reason": reason,
            "replicas": audits,
            "requests": self.counters.snapshot(),
            "leaked": self.gate.snapshot()["in_use"] != 0,
            "ok": all(a.get("ok") for a in audits) and
                  self.gate.snapshot()["in_use"] == 0,
        }
        self.drain_audit = audit
        tracer.instant("fleet_drain_done", ok=audit["ok"],
                       leaked=audit["leaked"])
        self._drained.set()
        return audit

    # -- probes -------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        out = {
            "status": "draining" if self.draining else "ok",
            "ready": self.ready,
            "uptime_s": round(time.time() - self.started_at, 3),
            "fleet": {
                "replicas": len(self.replicas),
                "ready_replicas": self.ready_count(),
                "retry_budget": self.retry_budget,
                "gate": self.gate.snapshot(),
            },
            "requests": self.counters.snapshot(),
            "replica": [r.snapshot() for r in self.replicas],
        }
        # numerics contract across the fleet: every replica advertises its
        # quant/spec config via /healthz (engine.stats()); cross-replica
        # bit-parity — what the fleet parity test and any response-equality
        # failover check rely on — is only meaningful between identically
        # configured engines, so a mixed fleet is surfaced loudly here
        configs = {}
        for r in self.replicas:
            s = r.last_health.get("serving") or {}
            if "serve_quant" in s:
                configs[r.idx] = {
                    "serve_quant": s.get("serve_quant"),
                    "spec_decode_k": s.get("spec_decode_k"),
                    "spec_drafter": s.get("spec_drafter"),
                }
        if configs:
            distinct = {json.dumps(c, sort_keys=True) for c in configs.values()}
            out["numerics"] = {
                "replica_configs": configs,
                "consistent": len(distinct) == 1,
            }
            if len(distinct) > 1:
                out.setdefault("degraded_reasons", []).append(
                    "numerics_config_mismatch"
                )
        if self.slo is not None:
            # the fleet's degradation view: the router's own SLO breaches
            # plus every replica's (probed /healthz carries them) — one
            # probe of the router answers "is anything in the fleet burning
            # its error budget, and which rule"
            reasons = out.get("degraded_reasons", [])
            for why in self.slo.degraded_reasons():
                if why not in reasons:
                    reasons.append(why)
            for r in self.replicas:
                for why in (r.last_health.get("degraded_reasons") or []):
                    tag = f"replica{r.idx}:{why}"
                    if tag not in reasons:
                        reasons.append(tag)
            out["degraded_reasons"] = reasons
        return out


def _make_handler(router: FleetRouter):
    class Handler(BaseHTTPRequestHandler):
        timeout = 600.0

        def _reply(self, code, payload, headers=None):
            data = json.dumps(payload).encode()
            try:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError, TimeoutError,
                    OSError):
                self.close_connection = True

        def _handle(self):
            route, _, query = self.path.partition("?")
            route = route.rstrip("/")
            if route == "/drain":
                rolling = "rolling=1" in query
                if rolling:
                    threading.Thread(target=router.rolling_drain,
                                     daemon=True).start()
                    return self._reply(200, {"status": "rolling_drain",
                                             "rolling": True})
                threading.Thread(target=drain_and_stop,
                                 args=(router, "POST /drain"),
                                 daemon=True).start()
                return self._reply(200, {
                    "status": "draining", "rolling": False,
                    "drain_timeout_s": router.drain_timeout_s,
                })
            if route != "/api":
                return self._reply(404, {"error": "use /api or /drain"})
            try:
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                code, payload, headers = router.handle_api(raw)
                return self._reply(code, payload, headers)
            except TimeoutError:
                self.close_connection = True
                return
            except Exception as e:  # noqa: BLE001 — surface to client
                router.counters.inc("failed")
                return self._reply(
                    500, {"error": f"{type(e).__name__}: {e}"}
                )

        do_POST = _handle
        do_PUT = _handle

        def do_GET(self):
            route = self.path.partition("?")[0].rstrip("/")
            if route == "/healthz":
                return self._reply(200, router.health())
            if route == "/readyz":
                if router.ready:
                    return self._reply(200, {
                        "ready": True,
                        "ready_replicas": router.ready_count(),
                    })
                return self._reply(503, {
                    "ready": False,
                    "status": ("draining" if router.draining
                               else "no_ready_replica"),
                    "ready_replicas": router.ready_count(),
                })
            if route == "/metrics":
                from galvatron_tpu.obs.prom import (
                    CONTENT_TYPE,
                    fleet_metrics_text,
                )

                try:
                    data = fleet_metrics_text(router).encode()
                except Exception as e:  # noqa: BLE001 — scrape must not kill routing
                    return self._reply(
                        500, {"error": f"{type(e).__name__}: {e}"}
                    )
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True
                return
            return self._reply(404, {
                "error": "use /api (POST/PUT), /healthz, /readyz, /metrics "
                         "(GET), or /drain[?rolling=1] (POST)"
            })

        def log_message(self, *a):  # quiet
            pass

    return Handler


def drain_and_stop(router: FleetRouter, reason: str) -> Dict[str, Any]:
    """The fleet shutdown sequence (SIGTERM and plain ``POST /drain``):
    drain + audit, then stop ``serve_forever`` so the process exits 0."""
    audit = router.drain(reason=reason)
    try:
        router.httpd.shutdown()
    except Exception:  # noqa: BLE001 — already stopped
        pass
    return audit


def serve_fleet_main(ns, raw_argv: Sequence[str]) -> int:
    """``cli serve-fleet`` entry: build the router from the parsed flags,
    forward everything non-fleet to the replicas, serve until drained."""
    import signal as _signal

    faults.init_from_env()
    if getattr(ns, "flight_dir", None) and not tracer.enabled:
        tracer.enable()
    ports = [int(p) for p in
             (ns.replica_ports or "").replace(" ", "").split(",") if p]
    router = FleetRouter(
        raw_argv,
        replicas=ns.replicas,
        replica_ports=ports or None,
        host=ns.host, port=ns.port,
        retry_budget=ns.retry_budget,
        request_ttl_s=ns.request_ttl_s if ns.request_ttl_s > 0 else None,
        drain_timeout_s=ns.drain_timeout_s,
        max_replica_restarts=ns.max_replica_restarts,
        restart_backoff_s=ns.replica_restart_backoff_s,
        probe_interval_s=ns.probe_interval_s,
        session_affinity=bool(ns.session_affinity),
        fleet_max_pending=ns.fleet_max_pending,
        fleet_dir=ns.fleet_dir,
        replica_faults=ns.replica_faults or "",
        rolling_shutdown=bool(ns.rolling_drain),
        num_slots_hint=ns.num_slots,
    )
    if getattr(ns, "slo", 0):
        from galvatron_tpu.obs.slo import SLOEngine, build_serving_rules

        router.slo = SLOEngine(
            rules=build_serving_rules(ns),
            events_path=os.path.join(router.fleet_dir, "slo_events.jsonl"),
            source="fleet",
        )
    # install the handler BEFORE spawning replicas: a SIGTERM landing in
    # the startup window would otherwise kill the router with the default
    # action and orphan every child it had already spawned
    try:
        _signal.signal(
            _signal.SIGTERM,
            lambda signum, frame: threading.Thread(
                target=drain_and_stop, args=(router, f"signal {signum}"),
                daemon=True,
            ).start(),
        )
    except ValueError:
        pass  # not the main thread
    router.start()
    print(f"fleet router listening on http://{router.host}:{router.port}/api "
          f"({len(router.replicas)} replicas)", flush=True)
    # serve_forever runs on the router's own thread (start()); this thread
    # just waits for the drain that SIGTERM or POST /drain will run
    try:
        router._drained.wait()
    except KeyboardInterrupt:
        drain_and_stop(router, "keyboard interrupt")
    audit = router.drain_audit
    try:
        router.httpd.shutdown()
        router.httpd.server_close()
    except Exception:  # noqa: BLE001 — already stopped
        pass
    print(f"fleet drained: ok={audit.get('ok')} "
          f"audit={json.dumps(audit)}", flush=True)
    if getattr(ns, "flight_dir", None):
        from galvatron_tpu.obs.flight import dump_flight

        dump_flight(ns.flight_dir, tracer, reason="fleet drained",
                    extra={"ok": audit.get("ok")})
    return 0 if audit.get("ok") else 1
