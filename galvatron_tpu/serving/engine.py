"""Continuous-batching generation engine: one jitted decode step, many requests.

The serialized server path (``server.GenerationService.generate`` under the
global lock) pays a full prefill+decode ``generate`` per request; aggregate
throughput is one request at a time no matter how many chips sit idle. This
engine instead runs ONE fixed-shape jitted decode step per iteration over a
persistent slot-based KV cache ([[kv_slots]]): every active request occupies
a batch row, new requests join between iterations via chunked prefill into
their slot, and finished rows retire and free their slot immediately
(iteration-level scheduling — Orca, OSDI '22). Overlapping requests share
every forward pass instead of queueing on a lock.

Static shapes are the point on TPU: a small DECLARED set of compiled
programs exists for the engine's whole lifetime — ``_decode_step`` at
``(num_slots, 1)``, ``_prefill_chunk`` at ``(1, prefill_chunk)``, plus
``_decode_verify`` at ``(num_slots, 1+k)`` when speculative decoding is on
(``spec_decode_k > 0``) — slot index, per-row offsets, and prompt contents
are all traced operands, so the jit cache stays bounded at the declared
count regardless of traffic mix (no per-request recompiles). The original
2-program pin grew deliberately: every member of the set is enumerable
up-front (aot/registry), swept by ``cli warmup``, and re-warmed on crash
recovery — an UNdeclared third program is still a bug the recompile guard
catches.

``--serve_quant int8`` swaps the fp weights for per-channel int8
(ops.quant) ONCE at engine load — the quantized avals flow into every
program key, so the int8 engine warms its own artifact set — and the load
parity-gates the measured max-abs logit drift against a declared bound.

``kv_num_blocks != 0`` swaps the contiguous slot cache for the paged
backend ([[paged_kv]]): K/V lives in a shared block pool addressed through
per-request block tables (a fixed ``(num_slots, max_blocks)`` int32 traced
operand), with copy-on-write prefix sharing and block-headroom admission.
The engine keeps the same pinned-program discipline — the paged prefill
and decode twins replace the slot pair one-for-one.

Sampling runs on host from the per-slot last logits: each request carries
its own temperature/top_k/top_p, which therefore never enter the compiled
program (a per-request static ``top_k`` would recompile; a host-side
``np.argmax``/categorical over ``(V,)`` per slot is noise next to the
forward). Greedy host sampling matches ``generate``'s on-device argmax
bit-for-bit, which is what the parity tests pin.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from galvatron_tpu.analysis.locks import lock_check_armed, lock_metrics, make_condition
from galvatron_tpu.core import faults
from galvatron_tpu.models import generation
from galvatron_tpu.models.generation import KVCache
from galvatron_tpu.models.modeling import ModelConfig
from galvatron_tpu.obs.tracing import tracer as _obs_tracer
from galvatron_tpu.serving import resilience as rz
from galvatron_tpu.serving import speculative
from galvatron_tpu.serving.kv_slots import SlotKVCache
from galvatron_tpu.serving.paged_kv import PagedKVCache
from galvatron_tpu.serving.scheduler import Request, Scheduler
from galvatron_tpu.utils.metrics import Counters, Histogram, QuantileWindow

#: decode-iteration latency bucket bounds (seconds): an iteration is
#: single-digit milliseconds on TPU and tens on CPU CI — the request-level
#: DEFAULT_LATENCY_BUCKETS would dump everything into the first bucket
_DECODE_STEP_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _prefill_chunk(params, cfg: ModelConfig, cache: KVCache, tokens, slot, offset):
    """Prefill one chunk of one request into its slot.

    tokens: (1, C) — the request's tokens [offset, offset+C) padded at the
    tail; slot/offset are traced scalars, so every chunk of every request
    reuses this one compiled program. Returns ((C, V) logits, cache).
    Garbage k/v written by tail padding is invisible forever: positions
    beyond a row's own query offset are causally masked, and each decode
    step overwrites its position before attending to it."""
    row = KVCache(
        jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1),
        jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1),
    )
    logits, row = generation.forward_with_cache(params, tokens, cfg, row, offset)
    cache = KVCache(
        jax.lax.dynamic_update_slice_in_dim(cache.k, row.k, slot, axis=1),
        jax.lax.dynamic_update_slice_in_dim(cache.v, row.v, slot, axis=1),
    )
    return logits[0], cache


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _decode_step(params, cfg: ModelConfig, cache: KVCache, tokens, offsets):
    """One decode iteration over ALL slots: tokens (B,) at per-row positions
    offsets (B,). Inactive rows carry (0, 0) — their write lands at position
    0 of their own free slot and is overwritten by the next prefill before
    any query can attend it. Returns ((B, V) next-position logits, cache)."""
    logits, cache = generation.forward_with_cache_slots(
        params, tokens[:, None], cfg, cache, offsets
    )
    return logits[:, 0], cache


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _decode_verify(params, cfg: ModelConfig, cache: KVCache, tokens, offsets):
    """Speculative verify step: tokens (B, 1+k) — column 0 is each row's
    sampled token, columns 1..k its drafted continuation — scored in ONE
    forward at per-row positions ``offsets`` (the per-row q_offset machinery
    that already powers chunked prefill handles s>1 rows natively). Returns
    ((B, 1+k, V) logits, cache): row logits[:, j] is the target
    distribution AFTER consuming column j, which is exactly what rejection
    sampling scores draft j+1 against. Rejected-draft k/v written at
    positions past the accepted length is overwritten by the next step's
    window before any query attends it — the same scatter-then-attend
    discipline the (0, 0) inactive rows rely on."""
    logits, cache = generation.forward_with_cache_slots(
        params, tokens, cfg, cache, offsets
    )
    return logits, cache


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def _paged_prefill_chunk(params, cfg: ModelConfig, pool: KVCache, tokens, table,
                         offset):
    """Paged twin of ``_prefill_chunk``: tokens (1, C) land in the request's
    blocks via its (1, max_blocks) table row; ``offset`` is a (1,) traced
    position. Tail-padding garbage goes to the null block or to positions
    past the query offset — invisible either way."""
    logits, pool = generation.forward_with_cache_paged(
        params, tokens, cfg, pool, table, offset
    )
    return logits[0], pool


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def _paged_decode_step(params, cfg: ModelConfig, pool: KVCache, tokens, tables,
                       offsets):
    """Paged twin of ``_decode_step``: one iteration over ALL slots, K/V
    addressed through the full (num_slots, max_blocks) table. Inactive rows
    carry (0, 0) and an all-null table row — their write lands in the null
    block, which is never attended."""
    logits, pool = generation.forward_with_cache_paged(
        params, tokens[:, None], cfg, pool, tables, offsets
    )
    return logits[:, 0], pool


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def _paged_decode_verify(params, cfg: ModelConfig, pool: KVCache, tokens,
                         tables, offsets):
    """Paged twin of ``_decode_verify``: the (B, 1+k) window lands in each
    row's blocks through the full table. Window positions past a row's
    reserved footprint resolve to the null block — written, never attended
    (only accepted positions are ever queried again, and acceptance is
    capped by the row's admission-time budget)."""
    logits, pool = generation.forward_with_cache_paged(
        params, tokens, cfg, pool, tables, offsets
    )
    return logits, pool


def _sample_host(rng: np.random.Generator, logits: np.ndarray,
                 temperature: float, top_k: int, top_p: float) -> int:
    """Host-side sampler mirroring ``generation.sample_logits`` semantics
    (temperature<=0 → greedy; top-k filter; nucleus keeps the smallest
    prefix with cumulative prob >= top_p, always >= 1 token). The processed
    distribution itself lives in ``generation.host_probs`` — shared with
    the speculative verifier, whose acceptance test must score drafts under
    the SAME distribution this sampler draws from."""
    logits = np.asarray(logits, np.float64)
    if temperature <= 0:
        return int(np.argmax(logits))
    p = generation.host_probs(logits, temperature, top_k, top_p)
    return int(rng.choice(len(p), p=p))


class Engine:
    """Continuous-batching engine: submit() → Future, loop thread does the rest.

    Thread model: handler threads call ``submit``/``stats``; ONE loop thread
    owns the device cache, the slot table, and all jit calls. The scheduler
    queue is the only structure both sides touch, and it carries its own lock.
    """

    def __init__(self, params, cfg: ModelConfig, *, num_slots: int = 4,
                 prefill_chunk: int = 32, max_queue: int = 64,
                 request_ttl_s: Optional[float] = 30.0,
                 max_seq_len: Optional[int] = None, eos_id: int = -1,
                 pad_id: int = 0, seed: int = 0,
                 result_timeout_s: float = 600.0, start_loop: bool = True,
                 deadline_policy: str = "partial",
                 max_engine_restarts: int = 3,
                 restart_backoff_s: float = 0.05,
                 drain_timeout_s: float = 30.0,
                 flight_dir: Optional[str] = None,
                 kv_block_size: int = 16,
                 kv_num_blocks: int = 0,
                 prefix_cache: bool = True,
                 serve_quant: str = "off",
                 quant_drift_max: float = 1.0,
                 spec_decode_k: int = 0,
                 spec_drafter: str = "prompt_lookup"):
        if deadline_policy not in ("partial", "fail"):
            raise ValueError(
                f"deadline_policy must be 'partial' or 'fail', got "
                f"{deadline_policy!r}"
            )
        if not cfg.causal or cfg.objective != "clm" or cfg.enc_layers > 0:
            raise ValueError(
                "serving engine requires a decoder-only causal LM (same "
                "constraint as generation.generate)"
            )
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if serve_quant not in ("off", "int8"):
            raise ValueError(
                f"serve_quant must be 'off' or 'int8', got {serve_quant!r}"
            )
        self.serve_quant = serve_quant
        self.quant_drift_max = float(quant_drift_max)
        self.quant_parity: Optional[dict] = None
        if serve_quant == "int8":
            # quantize ONCE, here — the step never touches fp weights — and
            # refuse to serve a quantization that left its accuracy budget:
            # the drift is measured on a probe forward, not assumed
            from galvatron_tpu.ops import quant as _quant

            qparams = _quant.quantize_params(params, cfg)
            self.quant_parity = _quant.parity_report(
                params, qparams, cfg, drift_max=self.quant_drift_max
            )
            params = qparams
        self.spec_k = int(spec_decode_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_decode_k must be >= 0, got {spec_decode_k}")
        self.spec_drafter = spec_drafter if self.spec_k > 0 else None
        self.drafter = (
            speculative.make_drafter(spec_drafter) if self.spec_k > 0 else None
        )
        self.params = params
        self.cfg = cfg
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self.seed = int(seed)
        self.result_timeout_s = float(result_timeout_s)
        # kv_num_blocks != 0 selects the paged backend: block-granular KV
        # with COW prefix sharing (serving/paged_kv.py); -1 sizes the pool
        # to the same HBM as the slot cache. 0 keeps the contiguous slot
        # cache. Both expose the same allocator surface to the engine.
        self.paged = int(kv_num_blocks) != 0
        if self.paged:
            self.slots = PagedKVCache(
                cfg, num_slots, block_size=kv_block_size,
                num_blocks=kv_num_blocks, max_seq_len=max_seq_len,
                prefix_cache=prefix_cache,
            )
        else:
            self.slots = SlotKVCache(cfg, num_slots, max_seq_len)
        # a chunk longer than the slot would slice past the cache end
        self.prefill_chunk = min(int(prefill_chunk), self.slots.max_seq_len)
        self.scheduler = Scheduler(max_queue=max_queue, default_ttl_s=request_ttl_s)
        self.deadline_policy = deadline_policy
        self.drain_timeout_s = float(drain_timeout_s)
        self.supervisor = rz.EngineSupervisor(
            max_restarts=max_engine_restarts, backoff_s=restart_backoff_s,
            flight_dir=flight_dir,
        )
        self.counters = Counters(
            "steps", "prefill_chunks", "prefill_tokens", "tokens_generated",
            "engine_restarts", "draft_proposed", "draft_accepted",
            "spec_steps", "spec_fallbacks",
        )
        self.ttft = QuantileWindow(512)
        # cumulative-bucket twins of the quantile windows: quantiles are the
        # single-process readout; bucket counts SUM across replicas, so the
        # fleet router aggregates these (snapshots ride /healthz → probe)
        self.ttft_hist = Histogram()
        self.latency_hist = Histogram()
        # per-ITERATION decode latency (the least-measured hot path until
        # now): finer buckets than the request-level histograms — one
        # iteration is milliseconds, not seconds
        self.decode_step_hist = Histogram(_DECODE_STEP_BUCKETS)
        # AOT artifact store for crash warm-rebuilds (set by warm_start);
        # summary of the most recent restart's warm-up, for tests/probes
        self._store = None
        self.last_restart_warm: Optional[dict] = None
        self._last_logits = np.zeros(
            (self.slots.num_slots, cfg.vocab_size), np.float32
        )
        self._by_slot: Dict[int, Request] = {}
        self._rng: Dict[int, np.random.Generator] = {}
        self._busy_s = 0.0
        self._last_step_tps = 0.0
        # GALVATRON_RECOMPILE_GUARD=1 (debug/CI): after the first decode
        # iteration, the engine's two programs exist — any further jit-cache
        # growth is a static-arg/shape leak compiling per request, and the
        # guard fails the offending step loudly (analysis/guards.py) instead
        # of letting latency quietly collapse. Per-engine baseline: other
        # engines compiling in the same process (different cfg) would show
        # as growth, so arm it on single-engine runs only.
        self._guard_armed = os.environ.get("GALVATRON_RECOMPILE_GUARD", "") not in ("", "0")
        self._guard_baseline = None
        self._cond = make_condition("engine.cond")
        self._stop = False  # guarded-by: self._cond
        self._draining = False
        self._closed = False
        self._working = False  # loop thread inside one admit+step iteration
        self._thread = threading.Thread(
            target=self._loop, name="serving-engine", daemon=True
        )
        if start_loop:
            self._thread.start()

    # -- client side --------------------------------------------------------

    def submit(self, tokens: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
               ttl_s: Optional[float] = None) -> Future:
        """Enqueue one request; the Future resolves to the full token list
        (prompt + completion, eos excluded — ``generate_np`` row semantics).
        Raises ``QueueFull`` on backpressure; the Future fails with
        ``RequestExpired`` if the request out-waits its TTL in queue."""
        return self.submit_request(
            tokens, max_new_tokens, temperature=temperature, top_k=top_k,
            top_p=top_p, ttl_s=ttl_s,
        ).future

    def submit_request(self, tokens: Sequence[int], max_new_tokens: int,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 0.0,
                       ttl_s: Optional[float] = None,
                       trace_id: Optional[str] = None) -> Request:
        """Like :meth:`submit` but returns the :class:`Request`, which
        carries the lifecycle state, ``finish_reason`` (deadline
        truncation), and the ``cancel()`` handle the server's disconnect
        poll uses. Refuses immediately — instead of parking a future that
        can never resolve — when the engine is draining or closed.
        ``trace_id`` is the fleet router's correlation id (propagated via
        the X-Galvatron-Trace-Id header, obs/correlate.py); it rides every
        lifecycle instant and the prefill span."""
        if self._closed:
            raise rz.EngineClosed(
                "engine is closed"
                + (" (crash-restart budget exhausted)"
                   if self.supervisor.gave_up else "")
            )
        if self._draining:
            raise rz.EngineDraining(
                "server is draining: not accepting new requests",
                retry_after_s=self.drain_timeout_s,
            )
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 0:
            raise ValueError("max_new_tokens must be >= 0")
        if not self.slots.fits(len(tokens), max_new_tokens):
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's slot capacity {self.slots.max_seq_len}"
            )
        req = Request(
            tokens=tokens, max_new_tokens=max_new_tokens,
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), trace_id=trace_id,
        )
        if trace_id is not None:
            _obs_tracer.instant("req_queued", rid=req.rid, tokens=len(tokens),
                                trace_id=trace_id)
        else:
            _obs_tracer.instant("req_queued", rid=req.rid, tokens=len(tokens))
        if max_new_tokens == 0:
            # counted as submitted too: terminal outcomes must partition the
            # submitted total or /metrics shows completed > submitted
            self.scheduler.counters.inc("submitted")
            rz.advance(req, rz.COMPLETED, self.scheduler.counters,
                       reason="zero_budget")
            req.finish_reason = "length"
            req.future.set_result(list(tokens))
            return req
        self.scheduler.submit(req, ttl_s=ttl_s)
        with self._cond:
            self._cond.notify()
        if self._closed:
            # close()/give-up raced the enqueue: the shutdown drain may have
            # run before our submit landed, and nothing will ever pop the
            # queue again — fail it here (idempotent if the drain got it)
            # so no caller is left holding a future that cannot resolve
            exc = rz.EngineClosed("engine shut down")
            self.scheduler.drain(exc)
            raise exc
        return req

    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
                 **kw) -> List[List[int]]:
        """Synchronous convenience over ``submit`` (bench/tests): submits all
        prompts at once so they overlap, then gathers in order."""
        futures = [self.submit(p, max_new_tokens, **kw) for p in prompts]
        return [f.result(timeout=self.result_timeout_s) for f in futures]

    def stats(self) -> dict:
        sc = self.scheduler.counters.snapshot()
        ec = self.counters.snapshot()
        ttft = self.ttft.summary()
        tokens = ec["tokens_generated"]
        busy = self._busy_s
        extra = {}
        if self.paged:
            extra = self.slots.block_stats()
            # per-request block footprint, keyed by rid (JSON-safe): what an
            # operator reads to see who is holding the pool
            extra["blocks_held"] = {
                str(req.rid): self.slots.blocks_held(slot)
                for slot, req in self._by_slot.items()
            }
        steps = ec["steps"]
        if lock_check_armed():
            # per-lock hold/contention counters from the runtime validator
            # (analysis/locks.py); the fleet router rolls these into
            # galvatron_lock_* /metrics families per replica
            extra["lock_stats"] = lock_metrics()
        return {
            "kv_backend": "paged" if self.paged else "slot",
            # the replica's numerics contract rides /healthz: the fleet
            # router refuses to mix replicas whose quant/spec config
            # disagrees (bit-parity across a fleet is only meaningful
            # between identically-configured engines)
            "serve_quant": self.serve_quant,
            "spec_decode_k": self.spec_k,
            "spec_drafter": self.spec_drafter,
            "quant_parity": self.quant_parity,
            # the capacity the replica ACTUALLY reserved (satellite of the
            # silent-clamp fix: a clamped --max_seq_len shows up here)
            "max_seq_len_effective": self.slots.max_seq_len,
            # crash-recovery warmth over HTTP: the chaos harness asserts a
            # restarted engine re-hit its programs in the artifact store
            "restart_warm": self.last_restart_warm,
            **extra,
            "queue_depth": self.scheduler.depth,
            "queue_capacity": self.scheduler.max_queue,
            "queue_saturated": self.scheduler.saturated,
            "active_slots": self.slots.active_count,
            "num_slots": self.slots.num_slots,
            "occupancy": round(self.slots.occupancy, 4),
            "steps": ec["steps"],
            "prefill_chunks": ec["prefill_chunks"],
            "prefill_tokens": ec["prefill_tokens"],
            "tokens_generated": tokens,
            "tokens_per_s": round(tokens / busy, 3) if busy > 0 else 0.0,
            "tokens_per_s_last_step": round(self._last_step_tps, 3),
            "ttft_p50_s": ttft["p50"],
            "ttft_p95_s": ttft["p95"],
            # the fleet bench reads the served tail per replica over HTTP
            "ttft_p99_s": self.ttft.quantile(0.99),
            # serializable cumulative-bucket snapshots: they ride /healthz
            # JSON to the fleet router, which sums them into the fleet-level
            # histograms (quantiles can't aggregate; buckets do)
            "ttft_hist": self.ttft_hist.snapshot(),
            "latency_hist": self.latency_hist.snapshot(),
            "decode_step_hist": self.decode_step_hist.snapshot(),
            # decode-speed observability (the "least-measured hot path"
            # satellite): tokens per decode iteration, batched over slots —
            # ~active-slot width without spec; rising above that width means
            # speculative acceptance is paying — plus the raw draft economy
            "accepted_tokens_per_step": (
                round(tokens / steps, 4) if steps else 0.0
            ),
            "draft_proposed": ec["draft_proposed"],
            "draft_accepted": ec["draft_accepted"],
            "draft_acceptance_rate": (
                round(ec["draft_accepted"] / ec["draft_proposed"], 4)
                if ec["draft_proposed"] else 0.0
            ),
            "spec_steps": ec["spec_steps"],
            "spec_fallbacks": ec["spec_fallbacks"],
            "submitted": sc["submitted"],
            "admitted": sc["admitted"],
            "completed": sc["completed"],
            "failed": sc["failed"],
            "rejected_queue_full": sc["rejected_queue_full"],
            "expired": sc["expired"],
            "expired_decode": sc["expired_decode"],
            "cancelled": sc["cancelled"],
            "cancelled_disconnect": sc["cancelled_disconnect"],
            "shed": sc["shed"],
            "engine_restarts": ec["engine_restarts"],
            "draining": self._draining,
            "alive": self.alive,
        }

    @property
    def alive(self) -> bool:
        """False once the engine is closed, drained, or gave up restarting
        — what ``/readyz`` keys on."""
        return not self._closed and not self.supervisor.gave_up

    @property
    def busy_retry_after_s(self) -> float:
        """Honest Retry-After hint for admission backpressure (queue full /
        pool saturated): the queue turns over at TTL granularity at worst,
        so a shed client retrying sooner than a fraction of it just burns
        its budget re-queueing."""
        ttl = self.scheduler.default_ttl_s
        return max(1.0, min(ttl if ttl else 5.0, 5.0))

    def reset_metrics(self) -> None:
        """Zero counters/TTFT/throughput accounting (bench: drop warmup
        compile time from the measured window). Call while idle."""
        self.counters = Counters(
            "steps", "prefill_chunks", "prefill_tokens", "tokens_generated",
            "engine_restarts", "draft_proposed", "draft_accepted",
            "spec_steps", "spec_fallbacks",
        )
        self.scheduler.counters = Scheduler.new_counters()
        # the supervisor's progress detection reads the completed counter:
        # its high-water mark must reset with it, or post-reset completions
        # never register as progress and the restart budget burns early
        self.supervisor.note_counter_reset()
        self.ttft = QuantileWindow(512)
        self.ttft_hist = Histogram()
        self.latency_hist = Histogram()
        self.decode_step_hist = Histogram(_DECODE_STEP_BUCKETS)
        self._busy_s = 0.0
        self._last_step_tps = 0.0

    def step_once(self) -> None:
        """One scheduler+decode iteration, synchronously (tests and
        ``start_loop=False`` callers — deterministic interleaving)."""
        self._admit()
        if self._by_slot:
            self._step()

    def begin_drain(self) -> None:
        """Flip into draining mode without blocking: admission closes
        (``submit`` raises ``EngineDraining``), queued-but-unstarted
        requests are shed fast with the distinct ``SHED`` status, in-flight
        slots keep decoding. Idempotent; :meth:`drain` adds the bounded
        wait + finalization."""
        with self._cond:
            if self._draining:
                return
            self._draining = True
            self._cond.notify_all()
        _obs_tracer.instant(
            "engine_drain_begin", active=self.slots.active_count,
            queued=self.scheduler.depth,
        )
        self.scheduler.shed_all(retry_after_s=self.drain_timeout_s)

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful shutdown: shed the queue, let in-flight slots run to
        completion under a bounded deadline, then stop the loop and close.
        Returns the post-drain invariant :meth:`audit` (zero leaked slots
        on every exit path is the contract the chaos harness pins)."""
        timeout_s = self.drain_timeout_s if timeout_s is None else float(timeout_s)
        self.begin_drain()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            # the allocator, not _by_slot, is the in-flight authority: a
            # request mid-PREFILL holds a slot before it reaches _by_slot,
            # and _working covers the pop→alloc gap inside one iteration —
            # closing under either would fail work the drain promised to
            # finish
            if (self.slots.active_count == 0 and self.scheduler.empty()
                    and not self._working):
                break
            if not self._thread.is_alive():
                break  # start_loop=False or a give-up: nothing will progress
            time.sleep(0.01)
        overran = [r.rid for r in self._by_slot.values()]
        # exit time past the deadline is bounded by ONE loop iteration (the
        # thread cannot be preempted mid-jit-dispatch, only asked to stop at
        # the next iteration boundary) — budget the join accordingly rather
        # than the blind 30 s shutdown default
        self.close(join_timeout_s=max(2.0, timeout_s))
        if overran:
            _obs_tracer.instant("engine_drain_overrun", rids=str(overran))
        audit = self.audit()
        _obs_tracer.instant("engine_drain_done", **{
            k: v for k, v in audit.items() if not isinstance(v, dict)})
        if self.supervisor.flight_dir:
            # every exit path leaves forensics, the graceful one included —
            # the chaos harness asserts a dump exists for drain AND crash
            from galvatron_tpu.obs.flight import dump_flight

            dump_flight(self.supervisor.flight_dir, _obs_tracer,
                        reason="graceful drain", extra=audit)
        return audit

    def audit(self) -> dict:
        """Post-drain/post-traffic invariant check: every slot returned to
        the free list, no request bookkeeping left behind, and (when the
        jit programs exist) the two-program pin intact. On the paged
        backend the block partition is part of the leak proof: after a
        drain every block must be FREE or CACHED (a cached prefix is kept
        warm deliberately — only an OWNED block with no owner is a leak)."""
        slot_audit = self.slots.audit()
        out = {
            "slots_ok": slot_audit["ok"],
            "active_slots": slot_audit["active"],
            "free_slots": slot_audit["free"],
            "num_slots": slot_audit["num_slots"],
            "tracked_requests": len(self._by_slot),
            "queue_depth": self.scheduler.depth,
            "leaked": (not slot_audit["ok"] or slot_audit["active"] != 0
                       or slot_audit["free"] != slot_audit["num_slots"]
                       or bool(self._by_slot)),
            "engine_restarts": self.counters.get("engine_restarts"),
        }
        if self.paged:
            out.update(
                blocks_ok=slot_audit["blocks_ok"],
                blocks_total=slot_audit["blocks_total"],
                blocks_free=slot_audit["blocks_free"],
                blocks_cached=slot_audit["blocks_cached"],
                blocks_active=slot_audit["blocks_active"],
            )
            out["leaked"] = bool(
                out["leaked"] or not slot_audit["blocks_ok"]
                or slot_audit["blocks_active"] != 0
            )
        return out

    def close(self, join_timeout_s: float = 30.0) -> None:
        self._closed = True
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.is_alive() and threading.current_thread() is not self._thread:
            self._thread.join(timeout=join_timeout_s)
        self._fail_all(rz.EngineClosed("engine shut down"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- engine loop (single thread owns cache + slots + jit calls) ---------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while (not self._stop and self.scheduler.empty()
                       and not self._by_slot):
                    # short timeout: TTLs must expire even with no wakeups
                    self._cond.wait(timeout=0.05)
                if self._stop:
                    break
            try:
                self._working = True
                try:
                    self._admit()
                    if self._by_slot:
                        self._step()
                finally:
                    self._working = False
            except Exception as e:  # noqa: BLE001 — engine must not die silently
                # in-process crash supervision (resilience.EngineSupervisor):
                # fail the unreplayable in-flight work fast, keep queued
                # requests with TTL budget, reset the KV cache, warm-rebuild,
                # and keep looping — give-up closes the engine for good
                try:
                    recovered = self.supervisor.on_crash(self, e)
                except Exception as e2:  # noqa: BLE001 — recovery failed
                    # a crash INSIDE recovery must not strand the loop
                    # thread with live futures: treat it as a give-up
                    self.supervisor.gave_up = True
                    recovered = False
                    e = e2
                if not recovered:
                    self._closed = True
                    self._fail_all(rz.EngineClosed(
                        f"engine gave up after "
                        f"{self.supervisor.restarts_total} restart(s): "
                        f"{type(e).__name__}: {e}"
                    ))
                    break

    def _admit(self) -> None:
        """Admit queued requests into free slots (chunked prefill). On the
        paged backend, admission additionally gates on BLOCK headroom: the
        head request stays queued (TTL still burning — that is the
        backpressure signal) until the pool's free + evictable blocks cover
        its worst-case footprint, so decode can never hit an empty pool."""
        self.scheduler.expire()
        while self.slots.free_slots > 0:
            if self.paged:
                head = self.scheduler.peek()
                if head is None:
                    return
                blocked = not (head.cancel_requested or head.future.cancelled()
                               ) and not self.slots.can_admit(
                    head.tokens, head.max_new_tokens, chunk=self.prefill_chunk
                )
                if blocked:
                    return
            req = self.scheduler.pop()
            if req is None:
                return
            if req.cancel_requested or req.future.cancelled():
                # abandoned while queued: terminal before ever taking a slot
                rz.advance(req, rz.CANCELLED, self.scheduler.counters,
                           reason=req.cancel_reason or "abandoned")
                if not req.future.done():
                    req.future.set_exception(rz.RequestCancelled(
                        f"request {req.rid} cancelled while queued "
                        f"({req.cancel_reason or 'abandoned'})"
                    ))
                continue
            try:
                self._prefill(req)
            except Exception as e:  # noqa: BLE001 — fail the one request
                if req.slot is not None:
                    self._by_slot.pop(req.slot, None)
                    self._rng.pop(req.slot, None)
                    self.slots.free(req.slot)
                    req.slot = None
                # a deadline that ran out DURING prefill is an expiry, not a
                # failure: no token was ever sampled, so both deadline
                # policies fail it with the TTL's own 503
                if isinstance(e, rz.DeadlineExceeded):
                    rz.advance(req, rz.EXPIRED, self.scheduler.counters,
                               where="prefill")
                else:
                    rz.advance(req, rz.FAILED, self.scheduler.counters,
                               reason=type(e).__name__)
                if not req.future.done():
                    req.future.set_exception(e)

    def _prefill(self, req: Request) -> None:
        # engine iteration spans (prefill/decode/sample) land on the same
        # process timeline as everything else; tracing off = no-op singleton.
        # The prefill span is per-request, so the fleet trace_id rides it
        # (batch-wide sample/decode spans cover many requests and don't).
        attrs = {"rid": req.rid, "tokens": len(req.tokens)}
        if req.trace_id is not None:
            attrs["trace_id"] = req.trace_id
        with _obs_tracer.span("prefill", **attrs):
            self._prefill_impl(req)

    def _prefill_impl(self, req: Request) -> None:
        t0 = time.perf_counter()
        slot = self.slots.alloc()
        assert slot is not None
        req.slot = slot
        rz.advance(req, rz.PREFILLING, slot=slot)
        toks = np.asarray(req.tokens, np.int32)
        c = self.prefill_chunk
        smax = self.slots.max_seq_len
        matched = 0
        if self.paged:
            # attach the longest cached prefix read-only and reserve the
            # request's WORST-CASE block footprint up front (evicting cold
            # prefix blocks if needed) — decode never allocates, so it can
            # never fail on pool pressure mid-request
            matched = self.slots.attach_prefix(slot, req.tokens)
            self.slots.reserve(slot, len(toks) + req.max_new_tokens)
        starts = list(range(matched, len(toks), c))
        if starts and starts[-1] + c > smax:
            # the fixed-size window must not cross the slot end:
            # dynamic_update_slice would CLAMP the start index, silently
            # shifting the write over earlier positions. Slide the last
            # window left instead — re-prefilling the overlap recomputes
            # identical k/v (deterministic function of tokens + positions),
            # so the rewrite is idempotent.
            starts[-1] = smax - c
        last_row = None
        for start in starts:
            # the deadline is end-to-end: a long prompt must not burn chip
            # time prefilling past the moment its client stops waiting
            if req.deadline is not None and time.time() > req.deadline:
                raise rz.DeadlineExceeded(
                    f"request {req.rid} deadline passed during prefill "
                    f"({start}/{len(toks)} tokens in)"
                )
            faults.prefill_chunk(self.counters.get("prefill_chunks"))
            chunk = toks[start:start + c]
            n = len(chunk)
            # fresh buffer per chunk: on CPU, jnp.asarray may alias the host
            # memory and dispatch is async — mutating a shared buffer for the
            # next chunk would corrupt the in-flight one's input
            buf = np.full((1, c), self.pad_id, np.int32)
            buf[0, :n] = chunk
            if self.paged:
                # the slid-left window may dip below the attached prefix —
                # COW-copy any shared/registered block the write covers
                # (recomputed k/v is identical; this protects the CACHE
                # entry and other holders, not this request's numerics)
                self.slots.ensure_writable(slot, start, min(start + c, smax))
                logits, pool = _paged_prefill_chunk(
                    self.params, self.cfg, self.slots.pool, jnp.asarray(buf),
                    jnp.asarray(self.slots.tables[slot:slot + 1]),
                    jnp.asarray([start], np.int32),
                )
                self.slots.pool = pool
            else:
                logits, cache = _prefill_chunk(
                    self.params, self.cfg, self.slots.cache, jnp.asarray(buf),
                    np.int32(slot), np.int32(start),
                )
                self.slots.cache = cache
            last_row = (logits, n - 1)
            self.counters.inc("prefill_chunks")
            self.counters.inc("prefill_tokens", n)
        logits, idx = last_row
        self._last_logits[slot] = np.asarray(logits[idx], np.float32)
        self.slots.lengths[slot] = len(toks)
        if self.paged:
            # publish the prompt's full blocks while the request decodes, so
            # a same-prefix request admitted next iteration already shares
            self.slots.register_prefix(slot, req.tokens)
        self._by_slot[slot] = req
        self._rng[slot] = np.random.default_rng((self.seed, req.rid))
        rz.advance(req, rz.DECODING, slot=slot)
        self._busy_s += time.perf_counter() - t0

    def _step(self) -> None:
        """One decode iteration: sample for every active slot from its last
        logits, retire eos/budget-exhausted/cancelled/over-deadline rows,
        then run ONE shared forward for the survivors."""
        t0 = time.perf_counter()
        # the chaos seam: engine_crash_at_iter raises here (the supervisor
        # must recover), slow_decode_ms stretches the iteration
        faults.engine_iteration(self.counters.get("steps"))
        tokens = np.zeros((self.slots.num_slots,), np.int32)
        offsets = np.zeros((self.slots.num_slots,), np.int32)
        sampled = 0
        appended = 0
        retired: List[int] = []
        cancelled: List[int] = []
        expired: List[int] = []
        with _obs_tracer.span("sample", active=self.slots.active_count):
            for slot in self.slots.active_slots():
                req = self._by_slot[slot]
                now = time.time()
                if req.cancel_requested or req.future.cancelled():
                    # a dead client must not keep burning its KV slot: the
                    # disconnect poll set the flag, the slot frees HERE, at
                    # decode-iteration granularity
                    cancelled.append(slot)
                    continue
                if req.deadline is not None and now > req.deadline:
                    # end-to-end deadline at decode-step granularity: one
                    # 4096-token hog can no longer starve everything behind it
                    expired.append(slot)
                    continue
                tok = _sample_host(
                    self._rng[slot], self._last_logits[slot],
                    req.temperature, req.top_k, req.top_p,
                )
                sampled += 1
                if req.first_token_at is None:
                    req.first_token_at = now
                    self.ttft.add(now - req.submitted_at)
                    self.ttft_hist.observe(now - req.submitted_at)
                if self.eos_id >= 0 and tok == self.eos_id:
                    req.finish_reason = "eos"
                    retired.append(slot)
                    continue
                req.generated.append(tok)
                appended += 1
                if len(req.generated) >= req.max_new_tokens:
                    req.finish_reason = "length"
                    retired.append(slot)
                    continue
                tokens[slot] = tok
                offsets[slot] = self.slots.lengths[slot]
                self.slots.lengths[slot] += 1
        for slot in retired:
            self._retire(slot)
        for slot in cancelled:
            self._retire_cancelled(slot)
        for slot in expired:
            self._retire_deadline(slot)
        still = self.slots.active_slots()
        drafts = self._build_drafts(still, offsets) if still else {}
        if still and drafts:
            appended += self._verify_step(still, tokens, offsets, drafts)
        elif still:
            with _obs_tracer.span("decode", active=len(still)):
                if self.paged:
                    for slot in still:
                        # provably a no-op today (decode writes past every
                        # registered/shared block), kept as a cheap COW
                        # invariant so a future sharing scheme cannot
                        # silently corrupt cached prefixes
                        off = int(offsets[slot])
                        self.slots.ensure_writable(slot, off, off + 1)
                    logits, pool = _paged_decode_step(
                        self.params, self.cfg, self.slots.pool,
                        jnp.asarray(tokens), jnp.asarray(self.slots.tables),
                        jnp.asarray(offsets),
                    )
                    self.slots.pool = pool
                else:
                    logits, cache = _decode_step(
                        self.params, self.cfg, self.slots.cache,
                        jnp.asarray(tokens), jnp.asarray(offsets),
                    )
                    self.slots.cache = cache
                # np.asarray is the engine's own readback sync (it needs the
                # logits on host to sample the next token), so the decode
                # span closes on realized compute, not dispatch
                logits = np.asarray(logits)
            for slot in still:
                self._last_logits[slot] = logits[slot]
        self.counters.inc("steps")
        self.counters.inc("tokens_generated", appended)
        if self._guard_armed:
            self.assert_cache_bounded()
        dt = time.perf_counter() - t0
        self._busy_s += dt
        if still:
            self.decode_step_hist.observe(dt)
        if dt > 0:
            self._last_step_tps = sampled / dt

    def _build_drafts(self, still, offsets) -> Dict[int, List[int]]:
        """Propose up to ``spec_k`` draft tokens per surviving slot from the
        prompt-lookup drafter. Returns {} — plain decode — when speculation
        is off, no row produced a draft (a wasted (1+k)-wide verify is pure
        overhead), or ANY surviving row lacks ``k+1`` positions of slot
        headroom: ``dynamic_update_slice`` CLAMPS an out-of-range window
        start, which would silently overwrite earlier cache positions (the
        same hazard the prefill slide-left handles), and the paged gather
        clamps table indices past ``max_seq_len`` the same way. Both the
        plain and verify programs are pinned and warm, so the per-iteration
        choice costs nothing."""
        if self.spec_k <= 0 or self.drafter is None:
            return {}
        k = self.spec_k
        smax = self.slots.max_seq_len
        drafts: Dict[int, List[int]] = {}
        for slot in still:
            if int(offsets[slot]) + 1 + k > smax:
                self.counters.inc("spec_fallbacks")
                return {}
            req = self._by_slot[slot]
            budget = req.max_new_tokens - len(req.generated)
            d = self.drafter.draft(
                list(req.tokens) + req.generated, min(k, budget)
            )
            if d:
                drafts[slot] = d
        return drafts

    def _verify_step(self, still, tokens, offsets,
                     drafts: Dict[int, List[int]]) -> int:
        """One speculative decode iteration: score every row's t0+drafts in
        a single (B, 1+k) forward, then run the rejection-sampling
        acceptance loop per row on host.

        Alignment: ``logits[slot, j]`` is the target distribution AFTER
        consuming window column j, so draft ``d[j]`` (window column j+1) is
        scored against ``logits[slot, j]``. On the first rejection the
        rejected token is struck (-inf) from the stored logits — the exact
        residual for a point-mass draft, and an argmax no-op under greedy
        (the rejected token was not the argmax by definition). On full
        acceptance ``logits[slot, len(d)]`` becomes the next iteration's
        sampling distribution. Rows without drafts ride along: their
        column-0 logits are exactly what the plain decode step would have
        produced."""
        k = self.spec_k
        batch = np.full((self.slots.num_slots, 1 + k), self.pad_id, np.int32)
        batch[:, 0] = tokens
        for slot, d in drafts.items():
            batch[slot, 1:1 + len(d)] = d
        with _obs_tracer.span("decode_verify", active=len(still), k=k):
            if self.paged:
                smax = self.slots.max_seq_len
                for slot in still:
                    off = int(offsets[slot])
                    self.slots.ensure_writable(slot, off, min(off + 1 + k, smax))
                logits, pool = _paged_decode_verify(
                    self.params, self.cfg, self.slots.pool,
                    jnp.asarray(batch), jnp.asarray(self.slots.tables),
                    jnp.asarray(offsets),
                )
                self.slots.pool = pool
            else:
                logits, cache = _decode_verify(
                    self.params, self.cfg, self.slots.cache,
                    jnp.asarray(batch), jnp.asarray(offsets),
                )
                self.slots.cache = cache
            logits = np.asarray(logits)  # (B, 1+k, V)
        self.counters.inc("spec_steps")
        appended = 0
        retired: List[int] = []
        for slot in still:
            req = self._by_slot[slot]
            d = drafts.get(slot, [])
            L = logits[slot]
            accepted = 0
            rejected_at = -1
            finish = None
            for j, dt in enumerate(d):
                if req.temperature <= 0:
                    ok = int(np.argmax(L[j])) == dt
                else:
                    p = generation.host_probs(
                        L[j], req.temperature, req.top_k, req.top_p
                    )
                    ok = self._rng[slot].random() < p[dt]
                if not ok:
                    rejected_at = j
                    break
                accepted += 1
                if self.eos_id >= 0 and dt == self.eos_id:
                    # matches the sampling loop: eos retires WITHOUT being
                    # appended to the completion
                    finish = "eos"
                    break
                req.generated.append(dt)
                appended += 1
                if len(req.generated) >= req.max_new_tokens:
                    finish = "length"
                    break
            self.counters.inc("draft_proposed", len(d))
            self.counters.inc("draft_accepted", accepted)
            if finish is not None:
                req.finish_reason = finish
                retired.append(slot)
                continue
            # only appended tokens advance the row's KV length (the eos /
            # budget cases above never reach here); rejected-draft k/v past
            # the new length is dead weight the next window overwrites
            self.slots.lengths[slot] += accepted
            if rejected_at >= 0:
                resid = np.asarray(L[rejected_at], np.float32).copy()
                resid[d[rejected_at]] = -np.inf
                self._last_logits[slot] = resid
            else:
                self._last_logits[slot] = L[len(d)]
        for slot in retired:
            self._retire(slot)
        return appended

    def assert_cache_bounded(self) -> None:
        """Pin the DECLARED compiled-program set for the engine lifetime:
        the first call records the post-warmup baseline, later calls raise
        ``RecompileError`` on any growth (a static-arg or shape leak). Each
        backend pins its own prefill + decode pair, plus the decode_verify
        program when speculative decoding is on — the 2-program pin became
        a declared set, not an open one; the paged backend's COW block copy
        (one shape forever) compiles lazily at the first shared write, so
        it stays outside the guard."""
        from galvatron_tpu.analysis.guards import RecompileError, cache_sizes

        if self.paged:
            fns = [_paged_prefill_chunk, _paged_decode_step]
            if self.spec_k > 0:
                fns.append(_paged_decode_verify)
        else:
            fns = [_prefill_chunk, _decode_step]
            if self.spec_k > 0:
                fns.append(_decode_verify)
        sizes = cache_sizes(tuple(fns))
        if self._guard_baseline is None:
            # warmup isn't over until BOTH programs exist: a first step whose
            # requests all retire before the shared forward (1-token answers,
            # instant eos) never compiles _decode_step, and baselining its
            # count at 0 would make the next request's legitimate warmup
            # compile trip the guard and fail every in-flight request
            if all(v > 0 for v in sizes.values()):
                self._guard_baseline = sizes
            return
        grown = {
            k: (self._guard_baseline[k], v)
            for k, v in sizes.items()
            if v > self._guard_baseline[k]
        }
        if grown:
            # re-baseline BEFORE raising: one recompile reports once — a
            # stale baseline would otherwise fail every subsequent step
            # (and request) against growth that already happened
            self._guard_baseline = sizes
            detail = ", ".join(f"{k}: {a}→{b}" for k, (a, b) in grown.items())
            raise RecompileError(
                f"serving engine recompiled after warmup ({detail}): a "
                "static argument or shape is varying per request"
            )

    def _release_slot(self, slot: int) -> Request:
        req = self._by_slot.pop(slot)
        self._rng.pop(slot, None)
        self.slots.free(slot)
        return req

    def _retire(self, slot: int) -> None:
        req = self._release_slot(slot)
        self.latency_hist.observe(time.time() - req.submitted_at)
        rz.advance(req, rz.COMPLETED, self.scheduler.counters,
                   reason=req.finish_reason)
        if not req.future.done():
            req.future.set_result(list(req.tokens) + req.generated)

    def _retire_cancelled(self, slot: int) -> None:
        req = self._release_slot(slot)
        reason = req.cancel_reason or "cancelled"
        rz.advance(req, rz.CANCELLED, self.scheduler.counters,
                   reason=reason, generated=len(req.generated))
        if not req.future.done():
            req.future.set_exception(rz.RequestCancelled(
                f"request {req.rid} cancelled mid-decode ({reason})"
            ))

    def _retire_deadline(self, slot: int) -> None:
        """Over-deadline DECODING request: the slot frees either way; the
        engine's ``deadline_policy`` decides whether the client gets the
        partial text (``"truncated": "deadline"``) or a deadline failure."""
        req = self._release_slot(slot)
        req.finish_reason = "deadline"
        rz.advance(req, rz.EXPIRED, self.scheduler.counters,
                   where="decode", generated=len(req.generated),
                   policy=self.deadline_policy)
        if req.future.done():
            return
        if self.deadline_policy == "partial":
            req.future.set_result(list(req.tokens) + req.generated)
        else:
            req.future.set_exception(rz.DeadlineExceeded(
                f"request {req.rid} exceeded its deadline after "
                f"{len(req.generated)}/{req.max_new_tokens} tokens"
            ))

    def _fail_all(self, exc: Exception) -> None:
        for slot in list(self._by_slot):
            req = self._release_slot(slot)
            rz.advance(req, rz.FAILED, self.scheduler.counters,
                       reason=type(exc).__name__)
            if not req.future.done():
                req.future.set_exception(exc)
        self.slots.reset()
        self.scheduler.drain(exc)

    def _crash_cleanup(self, exc: BaseException,
                       retry_after_s: Optional[float] = None) -> None:
        """Crash recovery, step 1 (called by the supervisor): fail the
        in-flight requests fast — continuous batching cannot replay
        mid-decode KV state, and the failed dispatch may have invalidated
        the donated cache buffers — and keep only the queued requests that
        still have TTL budget. ``retry_after_s`` (the supervisor's backoff)
        rides the failure so the 503 can carry an honest Retry-After."""
        wrapped = rz.EngineRestarted(
            f"engine restarted mid-request ({type(exc).__name__}: {exc}); "
            "please resubmit",
            retry_after_s=retry_after_s,
        )
        for slot in list(self._by_slot):
            req = self._release_slot(slot)
            rz.advance(req, rz.FAILED, self.scheduler.counters,
                       reason="engine_crash")
            if not req.future.done():
                req.future.set_exception(wrapped)
        self.slots.reset()
        self._last_logits[:] = 0.0
        # queued requests were never admitted: they survive the restart —
        # minus the ones whose TTL budget the crash already consumed
        self.scheduler.expire()

    def _warm_rebuild(self) -> None:
        """Crash recovery, step 2: re-warm the two pinned programs from the
        AOT artifact store (PR 9) so recovery costs cache-hit milliseconds,
        not a recompile. Best-effort — warmth is optional, serving is not."""
        if self._store is None:
            return
        try:
            from galvatron_tpu.aot import warmup as aot_warmup

            reports = self.warm_start(self._store, verbose=False)
            self.last_restart_warm = aot_warmup.summarize(reports)
        except Exception as e:  # noqa: BLE001 — recovery must not die warming
            _obs_tracer.instant("engine_warm_rebuild_failed", error=repr(e))

    def warm_start(self, store=None, verbose: bool = True) -> List[dict]:
        """AOT-compile the engine's two pinned programs from abstract inputs
        (galvatron_tpu/aot): with the persistent compile cache enabled, a
        server restart's first request pays a cache deserialize instead of
        two XLA compiles.  Call before serving traffic (the jit calls happen
        on the caller's thread; the loop thread only ever sees warm
        programs).  Returns the per-program warmup reports."""
        from galvatron_tpu.aot import registry as aot_registry
        from galvatron_tpu.aot import warmup as aot_warmup

        # keep the store: crash recovery re-warms from it (_warm_rebuild),
        # so an engine restart is an artifact-store hit, not a recompile
        if store is not None:
            self._store = store
        ctx = aot_registry.ProgramContext(
            cfg=self.cfg, num_slots=self.slots.num_slots,
            prefill_chunk=self.prefill_chunk, max_seq_len=self.slots.max_seq_len,
            kv_block_size=self.slots.block_size if self.paged else 16,
            kv_num_blocks=self.slots.num_blocks if self.paged else 0,
            serve_quant=self.serve_quant, spec_decode_k=self.spec_k,
        )
        specs = aot_registry.enumerate_programs(ctx, include=("serving",))
        return aot_warmup.warmup_programs(
            specs, store, plan=None, model_cfg=self.cfg, verbose=verbose
        )


# --- AOT program registration (galvatron_tpu/aot): the serving family -------
# The engine's whole design is "a small declared program set for the
# lifetime" — which makes it the cheapest possible warm-start: every member
# is enumerable from (ModelConfig, num_slots, prefill_chunk, serve_quant,
# spec_decode_k) with no weights. int8 engines derive their params avals
# through quantize_params under eval_shape, so the quantized dtype lands in
# every program key (plus an explicit key_extra term) — a warm fp store can
# never satisfy an int8 engine, and crash recovery re-warms the right set.


def _serving_programs(ctx):
    cfg = ctx.cfg
    if not cfg.causal or cfg.objective != "clm" or getattr(cfg, "enc_layers", 0) > 0:
        return []  # same constraint as the Engine ctor
    from galvatron_tpu.aot.registry import ProgramSpec
    from galvatron_tpu.models import modeling

    params_abs = jax.eval_shape(
        lambda k: modeling.init_model_params(k, cfg), jax.random.key(0)
    )
    serve_quant = str(getattr(ctx, "serve_quant", "off") or "off")
    spec_k = int(getattr(ctx, "spec_decode_k", 0) or 0)
    if serve_quant == "int8":
        from galvatron_tpu.ops import quant as _quant

        params_abs = jax.eval_shape(
            lambda p: _quant.quantize_params(p, cfg), params_abs
        )
    key_extra = (
        {"serve_quant": serve_quant} if serve_quant != "off" else None
    )
    max_len = int(min(ctx.max_seq_len or cfg.max_seq_len, cfg.max_seq_len))
    num_slots = max(1, int(ctx.num_slots))
    chunk = min(max(1, int(ctx.prefill_chunk)), max_len)
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
    kv_num_blocks = int(getattr(ctx, "kv_num_blocks", 0) or 0)
    if kv_num_blocks:
        # paged backend: the pool/table shapes are fully determined by
        # (block_size, num_blocks, max_len), so a warm restart re-hits the
        # same artifacts regardless of the allocator's runtime state
        block_size = max(1, int(ctx.kv_block_size))
        max_blocks = -(-max_len // block_size)
        if kv_num_blocks == -1:
            kv_num_blocks = num_slots * max_blocks + 1
        pool_abs = jax.eval_shape(
            lambda: generation.init_kv_cache(cfg, kv_num_blocks, block_size)
        )
        paged_meta = {"kv_block_size": block_size,
                      "kv_num_blocks": kv_num_blocks}
        if key_extra:
            paged_meta["key_extra"] = key_extra
        out = [
            ProgramSpec(
                "serving_paged_prefill", _paged_prefill_chunk,
                (params_abs, cfg, pool_abs, i32(1, chunk), i32(1, max_blocks),
                 i32(1)),
                meta={"donate": ("pool",), "num_slots": num_slots,
                      "prefill_chunk": chunk, **paged_meta},
            ),
            ProgramSpec(
                "serving_paged_decode", _paged_decode_step,
                (params_abs, cfg, pool_abs, i32(num_slots),
                 i32(num_slots, max_blocks), i32(num_slots)),
                meta={"donate": ("pool",), "num_slots": num_slots,
                      **paged_meta},
            ),
        ]
        if spec_k > 0:
            out.append(ProgramSpec(
                "serving_paged_decode_verify", _paged_decode_verify,
                (params_abs, cfg, pool_abs, i32(num_slots, 1 + spec_k),
                 i32(num_slots, max_blocks), i32(num_slots)),
                meta={"donate": ("pool",), "num_slots": num_slots,
                      "spec_decode_k": spec_k, **paged_meta},
            ))
        return out
    cache_abs = jax.eval_shape(
        lambda: generation.init_kv_cache(cfg, num_slots, max_len)
    )
    slot_meta = {"key_extra": key_extra} if key_extra else {}
    out = [
        ProgramSpec(
            "serving_prefill", _prefill_chunk,
            (params_abs, cfg, cache_abs, i32(1, chunk), i32(), i32()),
            meta={"donate": ("cache",), "num_slots": num_slots,
                  "prefill_chunk": chunk, **slot_meta},
        ),
        ProgramSpec(
            "serving_decode", _decode_step,
            (params_abs, cfg, cache_abs, i32(num_slots), i32(num_slots)),
            meta={"donate": ("cache",), "num_slots": num_slots, **slot_meta},
        ),
    ]
    if spec_k > 0:
        # the verify program's key carries k via the (B, 1+k) token aval —
        # sweeping --spec_decode_k at warmup warms each k separately
        out.append(ProgramSpec(
            "serving_decode_verify", _decode_verify,
            (params_abs, cfg, cache_abs, i32(num_slots, 1 + spec_k),
             i32(num_slots)),
            meta={"donate": ("cache",), "num_slots": num_slots,
                  "spec_decode_k": spec_k, **slot_meta},
        ))
    return out


def _register_aot_programs():
    from galvatron_tpu.aot.registry import register_program

    register_program(
        "serving", _serving_programs,
        programs=("serving_prefill", "serving_decode",
                  "serving_decode_verify",
                  "serving_paged_prefill", "serving_paged_decode",
                  "serving_paged_decode_verify"),
    )


_register_aot_programs()
