"""Iteration-level request scheduler (Orca, OSDI '22).

Requests enter a FIFO admission queue with a per-request deadline (TTL);
the engine loop admits the head of the queue whenever a KV slot frees up and
retires sequences the moment they hit eos or their token budget — admission
and retirement happen at *decode-step* granularity, between iterations of
one shared forward pass, never by preempting a running step.

Backpressure is explicit and accounted: a bounded queue rejects new work
immediately (``QueueFull`` → HTTP 503) instead of parking threads, and a
request that waits in queue past its deadline is expired with
``RequestExpired`` (→ 503) rather than eventually hogging a slot the live
traffic needs.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Deque, List, Optional

from galvatron_tpu.analysis.locks import make_lock
from galvatron_tpu.serving import resilience as rz
from galvatron_tpu.utils.metrics import Counters


class QueueFull(RuntimeError):
    """Admission queue at capacity — reject fast, client should back off."""


class RequestExpired(RuntimeError):
    """Request out-lived its TTL: waiting in the admission queue, or (since
    the deadline became end-to-end) mid-prefill before any token existed."""


_rid = itertools.count()


@dataclass
class Request:
    """One generation request moving through the lifecycle state machine
    (``resilience.STATES``): queue → slot → terminal state."""

    tokens: List[int]                 # prompt token ids
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    deadline: Optional[float] = None  # absolute time() the request may run to
    rid: int = field(default_factory=lambda: next(_rid))
    future: Future = field(default_factory=Future)
    submitted_at: float = field(default_factory=time.time)
    # engine-managed state
    slot: Optional[int] = None
    generated: List[int] = field(default_factory=list)
    first_token_at: Optional[float] = None
    state: str = rz.QUEUED
    cancel_requested: bool = False
    cancel_reason: Optional[str] = None
    # fleet-minted correlation id (obs/correlate.py): set only when the
    # router propagated X-Galvatron-Trace-Id (tracing armed); rides every
    # lifecycle instant + the prefill span so one id follows the request
    # across router → replica → failover replica
    trace_id: Optional[str] = None
    # terminal detail: "eos" | "length" | "deadline" (partial-policy
    # truncation — the server surfaces it as ``"truncated": "deadline"``)
    finish_reason: Optional[str] = None

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    def cancel(self, reason: str = "cancelled") -> None:
        """Ask the engine to stop this request at the next decode iteration
        (or skip it at admission). Thread-safe: a bool write under the GIL;
        the engine loop is the only reader that acts on it."""
        self.cancel_requested = True
        if self.cancel_reason is None:
            self.cancel_reason = reason


class Scheduler:
    """FIFO admission queue with TTL expiry and bounded depth."""

    def __init__(self, max_queue: int = 64, default_ttl_s: Optional[float] = 30.0):
        self.max_queue = max(1, int(max_queue))
        self.default_ttl_s = default_ttl_s
        self._lock = make_lock("scheduler.q")
        self._q: Deque[Request] = deque()  # guarded-by: self._lock
        self.counters = self.new_counters()

    @staticmethod
    def new_counters() -> Counters:
        """One counter per request outcome (``reset_metrics`` rebuilds the
        same set, so the two sites cannot drift)."""
        return Counters(
            "submitted", "admitted", "completed", "failed",
            "rejected_queue_full", "expired", "expired_decode",
            "cancelled", "cancelled_disconnect", "shed",
        )

    def submit(self, req: Request, ttl_s: Optional[float] = None) -> Request:
        """Enqueue or raise ``QueueFull``. ``ttl_s`` overrides the default
        TTL; None with no default means the request never expires."""
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        if ttl is not None and req.deadline is None:
            req.deadline = req.submitted_at + float(ttl)
        with self._lock:
            if len(self._q) >= self.max_queue:
                self.counters.inc("rejected_queue_full")
                raise QueueFull(
                    f"admission queue full ({self.max_queue} pending)"
                )
            self._q.append(req)
        self.counters.inc("submitted")
        return req

    def expire(self, now: Optional[float] = None) -> List[Request]:
        """Drop every queued request past its deadline, failing its future.
        Called by the engine loop each iteration — a saturated server sheds
        dead-on-arrival work instead of eventually generating for it."""
        now = time.time() if now is None else now
        dropped: List[Request] = []
        with self._lock:
            keep: Deque[Request] = deque()
            for r in self._q:
                if r.deadline is not None and now > r.deadline:
                    dropped.append(r)
                else:
                    keep.append(r)
            self._q = keep
        for r in dropped:
            rz.advance(r, rz.EXPIRED, self.counters, where="queue")
            if not r.future.done():  # client may have cancelled already
                r.future.set_exception(RequestExpired(
                    f"request {r.rid} expired after "
                    f"{now - r.submitted_at:.2f}s in queue"
                ))
        return dropped

    def peek(self, now: Optional[float] = None) -> Optional[Request]:
        """Head of the queue WITHOUT admitting it (expired ones shed
        first). The paged engine's admission gate reads the head's block
        footprint before deciding to pop — a request too big for current
        pool headroom stays queued, burning its own TTL as backpressure.
        Only the engine loop pops, so peek→pop cannot race another
        consumer."""
        self.expire(now)
        with self._lock:
            return self._q[0] if self._q else None

    def pop(self, now: Optional[float] = None) -> Optional[Request]:
        """Next admissible request (expired ones already shed), or None."""
        self.expire(now)
        with self._lock:
            if not self._q:
                return None
            req = self._q.popleft()
        self.counters.inc("admitted")
        return req

    def _drop_all(self, state: str, reason: str, exc_for) -> List[Request]:
        """Pop every queued request and terminate it: advance to ``state``
        and fail its future with ``exc_for(request)`` — the one copy of the
        pop-and-fail exit both :meth:`drain` and :meth:`shed_all` share."""
        with self._lock:
            dropped = list(self._q)
            self._q.clear()
        for r in dropped:
            if r.state not in rz.TERMINAL:  # double-drain race: already dropped
                rz.advance(r, state, self.counters, reason=reason)
            if not r.future.done():
                r.future.set_exception(exc_for(r))
        return dropped

    def drain(self, exc: Exception) -> List[Request]:
        """Fail every queued request (engine shutdown/crash give-up)."""
        return self._drop_all(rz.FAILED, "engine_shutdown", lambda r: exc)

    def shed_all(self, retry_after_s: Optional[float] = None) -> List[Request]:
        """Graceful drain: fail every queued-but-unstarted request fast with
        the distinct ``SHED`` status (503 → a load balancer retries against
        a peer) instead of making dead-on-arrival work wait out the drain."""
        return self._drop_all(
            rz.SHED, "draining",
            lambda r: rz.RequestShed(
                f"request {r.rid} shed: server draining "
                "(queued, generation not started)"
            ),
        )

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def saturated(self) -> bool:
        return self.depth >= self.max_queue

    def empty(self) -> bool:
        return self.depth == 0
