"""Structured metrics sink.

The reference's observability is print-based (SURVEY §5: loss printer
utils/training_utils.py:25-38, search progress prints, no structured sink;
the vendored Megatron tensorboard writer is unused). Here: a JSONL metrics
log — one flat JSON object per event with a monotonic step and wall-clock
timestamp — cheap, greppable, and loadable into anything.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when ``path`` is None.

    The file is opened in append mode (``O_APPEND``): each ``write`` lands
    atomically at the current end of file, so a restart (resume) appends
    after the previous run's records instead of truncating them. Multihost
    note: O_APPEND does NOT make concurrent writers from multiple processes
    safe on network filesystems — on a pod, only process 0 may own the path
    (the trainer guards this: every other process gets ``path=None``).
    A crash can still leave a torn FINAL line (a record cut mid-write);
    ``read_metrics`` skips it with a warning instead of failing the reader.
    """

    def __init__(self, path: Optional[str] = None, flush_every: int = 1):
        self.path = path
        self._f = None
        self._n = 0
        self.flush_every = max(1, flush_every)
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            _repair_torn_tail(path)
            self._f = open(path, "a")

    def log(self, event: str, step: Optional[int] = None, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"event": event, "ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        for k, v in fields.items():
            # scalars only: cast numpy/jax 0-d arrays, reject structures
            if hasattr(v, "item"):
                v = v.item()
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise TypeError(f"metric {k!r} must be scalar, got {type(v).__name__}")
            rec[k] = v
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._n += 1
            if self._n % self.flush_every == 0:
                self._f.flush()
        return rec

    def close(self):
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _repair_torn_tail(path: str) -> None:
    """Reopen-for-append repair: a crash mid-write can leave a final line
    with no trailing newline. Appending onto it would merge the resumed
    run's first record into the partial one — turning a skippable torn TAIL
    into mid-file corruption ``read_metrics`` rightly refuses. A tail that
    still parses as a complete JSON record just gets its newline; an
    unparseable tail is BY THE WRITER'S CONTRACT a partial record (records
    are written newline-terminated in one call) and is truncated away, with
    a warning."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return  # no existing file: nothing to repair
    if size == 0:
        return
    with open(path, "rb+") as f:
        window = min(size, 1 << 20)  # records are small; 1 MB is generous
        f.seek(size - window)
        data = f.read(window)
        if data.endswith(b"\n"):
            return
        nl = data.rfind(b"\n")
        tail = data[nl + 1:]
        if nl < 0 and window < size:
            # torn line longer than the window — implausible for this
            # writer; leave the bytes alone rather than truncate blind
            f.write(b"\n")
            return
        try:
            json.loads(tail)
            f.write(b"\n")  # complete record, just unterminated
            return
        except ValueError:
            pass
        warnings.warn(
            f"{path}: dropping torn final JSONL record from a previous "
            f"crash before appending: {tail[:80]!r}"
        )
        f.truncate(size - len(tail))


def read_metrics(path: str):
    """Load a JSONL metrics file. A torn FINAL line (crash mid-write — the
    writer appends record-at-a-time, so only the tail can be partial) is
    skipped with a warning; a malformed line anywhere ELSE is real
    corruption and still raises, chained to the offending line number."""
    with open(path) as f:
        raw = f.readlines()
    # physical line indices of the non-blank records: error messages must
    # name the line the operator will actually find in the file
    record_lines = [i for i, ln in enumerate(raw) if ln.strip()]
    out = []
    for pos, i in enumerate(record_lines):
        line = raw[i]
        try:
            out.append(json.loads(line))
        except ValueError as e:
            if pos == len(record_lines) - 1:
                warnings.warn(
                    f"{path}: skipping torn final JSONL record "
                    f"(crash mid-write): {line[:80]!r}"
                )
                break
            raise ValueError(
                f"{path}: malformed JSONL record on line {i + 1} "
                f"(not the final line, so not a torn tail): {line[:80]!r}"
            ) from e
    return out


class Counters:
    """Thread-safe named integer counters (serving: admitted/completed/
    rejected/expired and the server's succeeded/failed/rejected split —
    handler threads and the engine loop increment concurrently)."""

    def __init__(self, *names: str):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {n: 0 for n in names}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)


class QuantileWindow:
    """Fixed-size ring of float samples with quantile readout (serving:
    time-to-first-token p50/p95 over the last N requests). O(size) memory,
    sorting only at read time — add() stays cheap on the engine hot loop."""

    def __init__(self, size: int = 512):
        self.size = max(1, size)
        self._buf: list = []
        self._i = 0
        self._n = 0
        self._lock = threading.Lock()

    def add(self, x: float) -> None:
        with self._lock:
            if len(self._buf) < self.size:
                self._buf.append(float(x))
            else:
                self._buf[self._i] = float(x)
            self._i = (self._i + 1) % self.size
            self._n += 1

    def _snapshot(self) -> list:
        """Copy the ring under the lock. The copy is O(size) and cheap; the
        O(size log size) sort happens in ``quantile`` AFTER release, so a
        reader computing quantiles over a large window can never stall
        ``add()`` on the engine hot loop (pinned by test)."""
        with self._lock:
            return list(self._buf)

    def quantile(self, q: float) -> Optional[float]:
        buf = self._snapshot()
        if not buf:
            return None
        buf.sort()
        idx = min(len(buf) - 1, max(0, int(round(q * (len(buf) - 1)))))
        return buf[idx]

    def summary(self) -> Dict[str, Any]:
        return {"n": self._n, "p50": self.quantile(0.5), "p95": self.quantile(0.95)}
