"""Structured metrics sink.

The reference's observability is print-based (SURVEY §5: loss printer
utils/training_utils.py:25-38, search progress prints, no structured sink;
the vendored Megatron tensorboard writer is unused). Here: a JSONL metrics
log — one flat JSON object per event with a monotonic step and wall-clock
timestamp — cheap, greppable, and loadable into anything.
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Any, Dict, Optional

from galvatron_tpu.analysis.locks import make_lock


#: version stamped as a ``schema`` field on versioned JSONL records
#: (``train_iter``, ``slo_events``). Readers must tolerate records with a
#: HIGHER version and unknown extra fields (forward compatibility —
#: ``read_metrics`` parses without validation; a test pins the contract).
SCHEMA_VERSION = 1


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when ``path`` is None.

    The file is opened in append mode (``O_APPEND``): each ``write`` lands
    atomically at the current end of file, so a restart (resume) appends
    after the previous run's records instead of truncating them. Multihost
    note: O_APPEND does NOT make concurrent writers from multiple processes
    safe on network filesystems — on a pod, only process 0 may own the path
    (the trainer guards this: every other process gets ``path=None``).
    A crash can still leave a torn FINAL line (a record cut mid-write);
    ``read_metrics`` skips it with a warning instead of failing the reader.
    """

    def __init__(self, path: Optional[str] = None, flush_every: int = 1):
        self.path = path
        self._f = None
        self._n = 0
        self.flush_every = max(1, flush_every)
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            _repair_torn_tail(path)
            self._f = open(path, "a")

    def log(self, event: str, step: Optional[int] = None, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"event": event, "ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        for k, v in fields.items():
            # scalars only: cast numpy/jax 0-d arrays, reject structures
            if hasattr(v, "item"):
                v = v.item()
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise TypeError(f"metric {k!r} must be scalar, got {type(v).__name__}")
            rec[k] = v
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._n += 1
            if self._n % self.flush_every == 0:
                self._f.flush()
        return rec

    def close(self):
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _repair_torn_tail(path: str) -> None:
    """Reopen-for-append repair: a crash mid-write can leave a final line
    with no trailing newline. Appending onto it would merge the resumed
    run's first record into the partial one — turning a skippable torn TAIL
    into mid-file corruption ``read_metrics`` rightly refuses. A tail that
    still parses as a complete JSON record just gets its newline; an
    unparseable tail is BY THE WRITER'S CONTRACT a partial record (records
    are written newline-terminated in one call) and is truncated away, with
    a warning."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return  # no existing file: nothing to repair
    if size == 0:
        return
    with open(path, "rb+") as f:
        window = min(size, 1 << 20)  # records are small; 1 MB is generous
        f.seek(size - window)
        data = f.read(window)
        if data.endswith(b"\n"):
            return
        nl = data.rfind(b"\n")
        tail = data[nl + 1:]
        if nl < 0 and window < size:
            # torn line longer than the window — implausible for this
            # writer; leave the bytes alone rather than truncate blind
            f.write(b"\n")
            return
        try:
            json.loads(tail)
            f.write(b"\n")  # complete record, just unterminated
            return
        except ValueError:
            pass
        warnings.warn(
            f"{path}: dropping torn final JSONL record from a previous "
            f"crash before appending: {tail[:80]!r}"
        )
        f.truncate(size - len(tail))


def read_metrics(path: str):
    """Load a JSONL metrics file. A torn FINAL line (crash mid-write — the
    writer appends record-at-a-time, so only the tail can be partial) is
    skipped with a warning; a malformed line anywhere ELSE is real
    corruption and still raises, chained to the offending line number."""
    with open(path) as f:
        raw = f.readlines()
    # physical line indices of the non-blank records: error messages must
    # name the line the operator will actually find in the file
    record_lines = [i for i, ln in enumerate(raw) if ln.strip()]
    out = []
    for pos, i in enumerate(record_lines):
        line = raw[i]
        try:
            out.append(json.loads(line))
        except ValueError as e:
            if pos == len(record_lines) - 1:
                warnings.warn(
                    f"{path}: skipping torn final JSONL record "
                    f"(crash mid-write): {line[:80]!r}"
                )
                break
            raise ValueError(
                f"{path}: malformed JSONL record on line {i + 1} "
                f"(not the final line, so not a torn tail): {line[:80]!r}"
            ) from e
    return out


class Counters:
    """Thread-safe named integer counters (serving: admitted/completed/
    rejected/expired and the server's succeeded/failed/rejected split —
    handler threads and the engine loop increment concurrently)."""

    def __init__(self, *names: str):
        self._lock = make_lock("metrics.counters")
        self._c: Dict[str, int] = {n: 0 for n in names}  # guarded-by: self._lock

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)


#: default latency bucket bounds (seconds) shared by the serving TTFT and
#: e2e-latency histograms — fixed at construction so bucket counts from
#: every replica are mergeable by straight addition (quantiles are not)
DEFAULT_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics).

    Unlike :class:`QuantileWindow` this is *aggregatable*: two replicas'
    snapshots merge by adding per-bucket counts, so the fleet router can
    expose one true fleet-level distribution. observe() is O(buckets) with
    one lock — cheap enough for the engine hot loop."""

    def __init__(self, buckets=DEFAULT_LATENCY_BUCKETS):
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("Histogram needs at least one bucket bound")
        self.buckets = tuple(bs)
        self._lock = make_lock("metrics.histogram")
        self._counts = [0] * len(bs)  # guarded-by: self._lock — per-bucket (non-cumulative) counts
        self._overflow = 0            # guarded-by: self._lock — observations above the last bound
        self._sum = 0.0               # guarded-by: self._lock
        self._count = 0               # guarded-by: self._lock

    def observe(self, x: float) -> None:
        x = float(x)
        with self._lock:
            self._sum += x
            self._count += 1
            for i, b in enumerate(self.buckets):
                if x <= b:
                    self._counts[i] += 1
                    return
            self._overflow += 1

    def snapshot(self) -> Dict[str, Any]:
        """Serializable state: ``buckets`` maps each upper bound (as str,
        JSON keys must be strings) to its CUMULATIVE count; ``+Inf`` always
        present and equal to ``count``. This dict rides /healthz JSON from
        replica to router, where snapshots from N replicas merge."""
        with self._lock:
            counts = list(self._counts)
            overflow = self._overflow
            total = self._count
            s = self._sum
        out: Dict[str, Any] = {"sum": s, "count": total, "buckets": {}}
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out["buckets"][repr(b)] = cum
        out["buckets"]["+Inf"] = cum + overflow
        return out

    @staticmethod
    def merge_snapshots(snaps):
        """Sum histogram snapshots (e.g. one per replica) into one. Bucket
        bounds are unioned; mismatched bounds still merge correctly because
        counts are cumulative only per-snapshot — we re-accumulate from the
        union. Empty input → empty histogram snapshot."""
        merged_bounds = set()
        for s in snaps:
            merged_bounds.update(
                k for k in s.get("buckets", {}) if k != "+Inf"
            )
        bounds = sorted(merged_bounds, key=float)
        out: Dict[str, Any] = {"sum": 0.0, "count": 0, "buckets": {}}
        for b in bounds:
            out["buckets"][b] = 0
        out["buckets"]["+Inf"] = 0
        for s in snaps:
            out["sum"] += float(s.get("sum", 0.0))
            out["count"] += int(s.get("count", 0))
            sb = s.get("buckets", {})
            # de-cumulate this snapshot, then add into the union grid
            prev = 0
            items = sorted(
                ((float(k), int(v)) for k, v in sb.items() if k != "+Inf"),
            )
            per = []
            for bound, cumv in items:
                per.append((bound, cumv - prev))
                prev = cumv
            inf_extra = int(sb.get("+Inf", prev)) - prev
            for bound, delta in per:
                for ob in bounds:
                    if float(ob) >= bound:
                        # lands in the first union bucket that covers it
                        out["buckets"][ob] += delta
                        break
                else:
                    out["buckets"]["+Inf"] += delta
            out["buckets"]["+Inf"] += inf_extra
        # re-cumulate the union grid
        cum = 0
        for b in bounds:
            cum += out["buckets"][b]
            out["buckets"][b] = cum
        out["buckets"]["+Inf"] += cum
        return out


class QuantileWindow:
    """Fixed-size ring of float samples with quantile readout (serving:
    time-to-first-token p50/p95 over the last N requests). O(size) memory,
    sorting only at read time — add() stays cheap on the engine hot loop."""

    def __init__(self, size: int = 512):
        self.size = max(1, size)
        self._lock = make_lock("metrics.quantile_window")
        self._buf: list = []  # guarded-by: self._lock
        self._i = 0           # guarded-by: self._lock
        self._n = 0           # guarded-by: self._lock

    def add(self, x: float) -> None:
        with self._lock:
            if len(self._buf) < self.size:
                self._buf.append(float(x))
            else:
                self._buf[self._i] = float(x)
            self._i = (self._i + 1) % self.size
            self._n += 1

    def _snapshot(self) -> list:
        """Copy the ring under the lock. The copy is O(size) and cheap; the
        O(size log size) sort happens in ``quantile`` AFTER release, so a
        reader computing quantiles over a large window can never stall
        ``add()`` on the engine hot loop (pinned by test)."""
        with self._lock:
            return list(self._buf)

    def quantile(self, q: float) -> Optional[float]:
        buf = self._snapshot()
        if not buf:
            return None
        buf.sort()
        idx = min(len(buf) - 1, max(0, int(round(q * (len(buf) - 1)))))
        return buf[idx]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            n = self._n
        return {"n": n, "p50": self.quantile(0.5), "p95": self.quantile(0.95)}
