"""Structured metrics sink.

The reference's observability is print-based (SURVEY §5: loss printer
utils/training_utils.py:25-38, search progress prints, no structured sink;
the vendored Megatron tensorboard writer is unused). Here: a JSONL metrics
log — one flat JSON object per event with a monotonic step and wall-clock
timestamp — cheap, greppable, and loadable into anything.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when ``path`` is None."""

    def __init__(self, path: Optional[str] = None, flush_every: int = 1):
        self.path = path
        self._f = None
        self._n = 0
        self.flush_every = max(1, flush_every)
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")

    def log(self, event: str, step: Optional[int] = None, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"event": event, "ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        for k, v in fields.items():
            # scalars only: cast numpy/jax 0-d arrays, reject structures
            if hasattr(v, "item"):
                v = v.item()
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise TypeError(f"metric {k!r} must be scalar, got {type(v).__name__}")
            rec[k] = v
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._n += 1
            if self._n % self.flush_every == 0:
                self._f.flush()
        return rec

    def close(self):
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_metrics(path: str):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class Counters:
    """Thread-safe named integer counters (serving: admitted/completed/
    rejected/expired and the server's succeeded/failed/rejected split —
    handler threads and the engine loop increment concurrently)."""

    def __init__(self, *names: str):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {n: 0 for n in names}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._c)


class QuantileWindow:
    """Fixed-size ring of float samples with quantile readout (serving:
    time-to-first-token p50/p95 over the last N requests). O(size) memory,
    sorting only at read time — add() stays cheap on the engine hot loop."""

    def __init__(self, size: int = 512):
        self.size = max(1, size)
        self._buf: list = []
        self._i = 0
        self._n = 0
        self._lock = threading.Lock()

    def add(self, x: float) -> None:
        with self._lock:
            if len(self._buf) < self.size:
                self._buf.append(float(x))
            else:
                self._buf[self._i] = float(x)
            self._i = (self._i + 1) % self.size
            self._n += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return None
        idx = min(len(buf) - 1, max(0, int(round(q * (len(buf) - 1)))))
        return buf[idx]

    def summary(self) -> Dict[str, Any]:
        return {"n": self._n, "p50": self.quantile(0.5), "p95": self.quantile(0.95)}
