"""Structured metrics sink.

The reference's observability is print-based (SURVEY §5: loss printer
utils/training_utils.py:25-38, search progress prints, no structured sink;
the vendored Megatron tensorboard writer is unused). Here: a JSONL metrics
log — one flat JSON object per event with a monotonic step and wall-clock
timestamp — cheap, greppable, and loadable into anything.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL metrics writer; no-op when ``path`` is None."""

    def __init__(self, path: Optional[str] = None, flush_every: int = 1):
        self.path = path
        self._f = None
        self._n = 0
        self.flush_every = max(1, flush_every)
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")

    def log(self, event: str, step: Optional[int] = None, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"event": event, "ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        for k, v in fields.items():
            # scalars only: cast numpy/jax 0-d arrays, reject structures
            if hasattr(v, "item"):
                v = v.item()
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise TypeError(f"metric {k!r} must be scalar, got {type(v).__name__}")
            rec[k] = v
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._n += 1
            if self._n % self.flush_every == 0:
                self._f.flush()
        return rec

    def close(self):
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_metrics(path: str):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
