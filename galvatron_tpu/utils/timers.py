"""Named wall-clock timers (reference: site_package/megatron/timers.py:123
``Timers`` — start/stop/elapsed named timers with a log-string formatter;
that implementation barriers over torch.distributed and reads CUDA events,
neither of which exists here: on TPU the caller is responsible for
``jax.block_until_ready`` at measurement boundaries, which the runtime
profiler (galvatron_tpu.profiling.runtime) already does)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._started: Optional[float] = None
        self.count = 0

    def start(self):
        if self._started is not None:
            raise RuntimeError(f"timer {self.name!r} already started")
        self._started = time.perf_counter()

    def stop(self):
        if self._started is None:
            raise RuntimeError(f"timer {self.name!r} not started")
        self._elapsed += time.perf_counter() - self._started
        self._started = None
        self.count += 1

    def elapsed(self, reset: bool = False, running_ok: bool = False) -> float:
        """Total elapsed seconds.

        A currently-running interval is INCLUDED when ``running_ok=True``
        (crash dumps read timers mid-span — silently excluding the open
        interval would under-report exactly the phase that crashed);
        otherwise reading a running timer raises, so the old
        silently-wrong readout can't happen by accident. With both
        ``running_ok`` and ``reset``, the open interval restarts at now
        so the included portion is never counted twice."""
        now = time.perf_counter()
        e = self._elapsed
        if self._started is not None:
            if not running_ok:
                raise RuntimeError(
                    f"timer {self.name!r} is running; pass running_ok=True to "
                    "include the open interval (e.g. a crash-path readout)"
                )
            e += now - self._started
        if reset:
            self._elapsed = 0.0
            self.count = 0
            if self._started is not None:
                self._started = now
        return e


class Timers:
    """``timers('fwd').start() ... .stop(); timers.log(['fwd'])``"""

    def __init__(self):
        self._timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name)
        return self._timers[name]

    def names(self) -> List[str]:
        return list(self._timers)

    def log_string(
        self, names: Optional[List[str]] = None, normalizer: float = 1.0, reset: bool = True
    ) -> str:
        """(reference: Timers.log, megatron/timers.py — 'time (ms)' line)"""
        assert normalizer > 0.0
        parts = []
        for name in names or self.names():
            if name in self._timers:
                # running_ok: a periodic log readout mid-phase is exactly the
                # open-interval case elapsed()'s raise exists to surface —
                # here the inclusion is wanted, not an accident
                ms = (
                    self._timers[name].elapsed(reset=reset, running_ok=True)
                    * 1000.0 / normalizer
                )
                parts.append(f"{name}: {ms:.2f}")
        return "time (ms) | " + " | ".join(parts)
