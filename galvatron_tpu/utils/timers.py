"""Named wall-clock timers (reference: site_package/megatron/timers.py:123
``Timers`` — start/stop/elapsed named timers with a log-string formatter;
that implementation barriers over torch.distributed and reads CUDA events,
neither of which exists here: on TPU the caller is responsible for
``jax.block_until_ready`` at measurement boundaries, which the runtime
profiler (galvatron_tpu.profiling.runtime) already does)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._started: Optional[float] = None
        self.count = 0

    def start(self):
        if self._started is not None:
            raise RuntimeError(f"timer {self.name!r} already started")
        self._started = time.perf_counter()

    def stop(self):
        if self._started is None:
            raise RuntimeError(f"timer {self.name!r} not started")
        self._elapsed += time.perf_counter() - self._started
        self._started = None
        self.count += 1

    def elapsed(self, reset: bool = False) -> float:
        """Total elapsed seconds (not counting a currently-running interval)."""
        e = self._elapsed
        if reset:
            self._elapsed = 0.0
            self.count = 0
        return e


class Timers:
    """``timers('fwd').start() ... .stop(); timers.log(['fwd'])``"""

    def __init__(self):
        self._timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name)
        return self._timers[name]

    def names(self) -> List[str]:
        return list(self._timers)

    def log_string(
        self, names: Optional[List[str]] = None, normalizer: float = 1.0, reset: bool = True
    ) -> str:
        """(reference: Timers.log, megatron/timers.py — 'time (ms)' line)"""
        assert normalizer > 0.0
        parts = []
        for name in names or self.names():
            if name in self._timers:
                ms = self._timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        return "time (ms) | " + " | ".join(parts)
