"""JSON interchange for profiled data (reference: galvatron/utils/
config_utils.py:34-116 — the bandwidth/time/memory config readers/writers).

Schemas:

computation profiling (reference computation_profiling_*.json equivalent):
  {"layertype_0": <fwd ms per layer per sample>, ...,
   "other": <embed/cls fwd ms per sample>}

memory profiling (reference memory_profiling_*.json equivalent):
  {"layertype_0": {"parameter_mb": ..., "activation_mb_per_sample": {"1": ...},
                   "boundary_activation_mb_per_sample": ...},
   "other": {"param_mb": ..., "act_mb_per_sample": ...}}

(all time quantities live in the computation JSON so a memory-only profile
run never persists placeholder timings; older files carrying
other.fwd_ms_per_sample in the memory JSON still load)

hardware (reference allreduce_bandwidth_*/p2p_bandwidth_*/overlap_coefficient
.json equivalents, measured over ICI instead of nccl-tests):
  {"allreduce": {"<size>_<consec01>": GBps}, "p2p": {"<pp>": GBps},
   "overlap_coe": float}
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

from galvatron_tpu.search.cost_model import (
    ProfiledHardware,
    ProfiledLayerType,
    ProfiledModelCosts,
)


def read_json_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_json_config(obj: Dict[str, Any], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f, indent=2)


def save_profiled_model(costs: ProfiledModelCosts, time_path=None, mem_path=None) -> None:
    """Write either or both profiled-model JSONs (None skips that file)."""
    if time_path:
        times: Dict[str, Any] = {
            f"layertype_{i}": lt.fwd_ms_per_sample for i, lt in costs.layer_types.items()
        }
        times["other"] = costs.other_fwd_ms_per_sample
        write_json_config(times, time_path)
    if mem_path:
        mem: Dict[str, Any] = {}
        for i, lt in costs.layer_types.items():
            mem[f"layertype_{i}"] = {
                "parameter_mb": lt.parameter_mb,
                "activation_mb_per_sample": {
                    str(k): v for k, v in lt.activation_mb_per_sample.items()
                },
                "boundary_activation_mb_per_sample": lt.boundary_activation_mb_per_sample,
                "moe_expert_param_fraction": lt.moe_expert_param_fraction,
                "moe_a2a_mb_per_sample": lt.moe_a2a_mb_per_sample,
                "moe_expert_time_fraction": lt.moe_expert_time_fraction,
            }
        mem["other"] = {
            "param_mb": costs.other_param_mb,
            "act_mb_per_sample": costs.other_act_mb_per_sample,
            "hidden_size": costs.hidden_size,
            "measured_vocab_slope_ms": {
                str(k): v for k, v in costs.measured_vocab_slope_ms.items()
            },
            "measured_vocab_const_ms": {
                str(k): v for k, v in costs.measured_vocab_const_ms.items()
            },
            "measured_vocab_mp": costs.measured_vocab_mp,
        }
        write_json_config(mem, mem_path)


def load_profiled_model(time_path: str, mem_path: str) -> ProfiledModelCosts:
    times = read_json_config(time_path)
    mem = read_json_config(mem_path)
    layer_types: Dict[int, ProfiledLayerType] = {}
    for key, t in times.items():
        if not key.startswith("layertype_"):
            continue
        i = int(key.split("_")[1])
        m = mem[key]
        try:
            layer_types[i] = _load_layer_type(t, m)
        except ValueError as e:
            raise ValueError(
                f"profile {mem_path!r} ({key}) carries invalid data — likely "
                "written by an older profiler revision (a pre-fix MoE profile "
                "has moe_expert_param_fraction > 1): re-run `profile` to "
                f"regenerate it. Original error: {e}"
            ) from e
    other = mem.get("other", {})
    other_ms = times.get("other", other.get("fwd_ms_per_sample", 0.0))
    return ProfiledModelCosts(
        layer_types=layer_types,
        other_param_mb=float(other.get("param_mb", 0.0)),
        other_act_mb_per_sample=float(other.get("act_mb_per_sample", 0.0)),
        other_fwd_ms_per_sample=float(other_ms),
        hidden_size=int(other.get("hidden_size", 0)),
        measured_vocab_slope_ms={
            int(k): float(v)
            for k, v in other.get("measured_vocab_slope_ms", {}).items()
        },
        measured_vocab_const_ms={
            int(k): float(v)
            for k, v in other.get("measured_vocab_const_ms", {}).items()
        },
        measured_vocab_mp=str(other.get("measured_vocab_mp", "")),
    )


def _load_layer_type(t, m) -> ProfiledLayerType:
    return ProfiledLayerType(
        fwd_ms_per_sample=float(t),
        parameter_mb=float(m["parameter_mb"]),
        activation_mb_per_sample={
            int(k): float(v) for k, v in m["activation_mb_per_sample"].items()
        },
        boundary_activation_mb_per_sample=float(m["boundary_activation_mb_per_sample"]),
        moe_expert_param_fraction=float(m.get("moe_expert_param_fraction", 0.0)),
        moe_a2a_mb_per_sample=float(m.get("moe_a2a_mb_per_sample", 0.0)),
        moe_expert_time_fraction=(
            None
            if m.get("moe_expert_time_fraction") is None
            else float(m["moe_expert_time_fraction"])
        ),
    )


def save_profiled_hardware(hw: ProfiledHardware, path: str) -> None:
    write_json_config(
        {
            "allreduce": hw.allreduce_bw,
            "p2p": {str(k): v for k, v in hw.p2p_bw.items()},
            "overlap_coe": hw.overlap_coe,
            "dcn_keys": list(hw.dcn_keys),
        },
        path,
    )


def load_profiled_hardware(path: str) -> ProfiledHardware:
    d = read_json_config(path)
    return ProfiledHardware(
        allreduce_bw={str(k): float(v) for k, v in d.get("allreduce", {}).items()},
        p2p_bw={int(k): float(v) for k, v in d.get("p2p", {}).items()},
        overlap_coe=float(d.get("overlap_coe", 1.1)),
        dcn_keys=list(d.get("dcn_keys", [])),
    )
