"""AOT warmup: compile every registered program from abstract shapes.

``cli warmup`` (and the trainer's startup consult, and the elastic child's
re-plan prewarm) all funnel through here: enumerate the programs a
(plan × ModelConfig × mesh) run needs (`aot/registry.py`), ``lower`` each
from its abstract inputs, ``compile``, and account the result against the
plan-keyed manifest (`aot/cache.py`).  With the persistent compile cache
enabled, a warmed program's next compile — in ANY process on this host —
is a disk deserialize instead of an XLA compile, which is what turns a
trainer start, an elastic restart, or a serving cold-start into a cache
lookup.

Failure isolation is the contract: one program failing to compile (this
container's protobuf pipeline-compile crash class, a backend without some
feature) degrades to a per-program ``status: failed`` report and a printed
warning — it must never abort the sweep, because the other programs' warmth
is exactly as valuable without it.

Each report also carries the compiled program's ``memory_analysis`` peak
buffer numbers where the backend exposes them, next to the cost model's
analytic prediction — the same number GTA015 gates plans on — so a warmup
sweep doubles as a cheap feasibility cross-check.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from galvatron_tpu.aot import cache as aot_cache
from galvatron_tpu.aot import registry as aot_registry


def force_cpu_world(n_devices: int) -> None:
    """Simulate an ``n_devices``-wide CPU platform (``cli warmup
    --force_world``; the elastic child's sim-world bootstrap delegates
    here): programmatic XLA_FLAGS append + platform pin — env vars alone
    are overridden where a sitecustomize pre-imports jax.  Must run before
    the first backend touch; permanently redirects this process to CPU."""
    import jax

    flag = f"--xla_force_host_platform_device_count={int(n_devices)}"
    cur = os.environ.get("XLA_FLAGS", "")
    if flag not in cur.split():  # idempotent: a duplicate token would also
        # key the compile cache apart from a run whose env already had it
        os.environ["XLA_FLAGS"] = (cur + " " + flag).strip()
    jax.config.update("jax_platforms", "cpu")


def memory_stats(compiled) -> Optional[Dict[str, float]]:
    """Peak-buffer numbers from the compiled program's ``memory_analysis``:
    state (arguments + outputs − aliased, so a donated train state counts
    once) and temp (grads + activations + scratch) in MB — the decomposition
    `search/memory_fidelity.py` validates the cost model against."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — optional per backend
        return None
    if ma is None:
        return None
    try:
        state = (
            ma.argument_size_in_bytes
            + ma.output_size_in_bytes
            - ma.alias_size_in_bytes
        ) / 1e6
        temp = ma.temp_size_in_bytes / 1e6
        out = {
            "state_mb": round(state, 3),
            "temp_mb": round(temp, 3),
            "total_mb": round(state + temp, 3),
        }
        code = getattr(ma, "generated_code_size_in_bytes", None)
        if code is not None:
            out["code_bytes"] = int(code)
        return out
    except AttributeError:
        return None


def predicted_train_memory_mb(cfg, hp, world: int, global_bsz: int) -> Optional[float]:
    """The cost model's analytic per-device MB for this plan — the exact
    number the GTA015 feasibility check gates on — so warmup reports carry
    predicted-vs-compiled memory side by side.  None where the analytic
    pricing does not apply (vision/MoE corner shapes)."""
    try:
        from galvatron_tpu.search.memory_fidelity import predicted_train_mb
        from galvatron_tpu.search.theoretical import analytic_model_costs

        return round(
            predicted_train_mb(analytic_model_costs(cfg), cfg, hp, world, global_bsz),
            1,
        )
    except Exception:  # noqa: BLE001 — a cross-check must not fail the sweep
        return None


def compile_program(
    spec: aot_registry.ProgramSpec,
    store: Optional[aot_cache.ArtifactStore] = None,
    *,
    plan: Any = None,
    model_cfg: Any = None,
    serialize: bool = False,
    verbose: bool = True,
    footprint_sink: Any = None,
) -> Dict[str, Any]:
    """AOT-lower + compile ONE program, failure-isolated.

    Returns ``{program, key, status: compiled|failed, cache_hit, lower_ms,
    compile_ms, memory, error}``.  ``lower_ms`` (tracing + StableHLO
    emission) is split from ``compile_ms`` (XLA) so these rows are directly
    comparable with the lower-only comm auditor's numbers.  ``cache_hit`` is
    manifest-based: the key was recorded by an earlier warmup/run, so the
    persistent cache serves the executable and ``compile_ms`` is
    deserialization, not XLA.  ``footprint_sink``, when given, is called
    with each program's lowered StableHLO text as
    ``footprint_sink(spec, text)`` — the warmup comm-footprint hook
    (sink failures are isolated like everything else here)."""
    from galvatron_tpu.obs.tracing import tracer

    key = None
    try:
        key = aot_cache.program_key(
            spec.name,
            plan=plan,
            # the spec's executed config (what the engine actually compiled
            # from) beats the caller's pre-build view for keying — the two
            # must agree between a prewarm and a later startup consult
            model_cfg=spec.meta.get("exec_cfg", model_cfg),
            abstract_args=spec.args,
            abstract_kwargs=spec.kwargs,
            donate=spec.meta.get("donate"),
            extra=spec.meta.get("key_extra"),
        )
    except Exception as e:  # noqa: BLE001 — keying must not abort the sweep
        if verbose:
            print(f"aot: keying {spec.name} failed: {type(e).__name__}: {e}")
    hit = bool(store is not None and key is not None and store.lookup(key))
    report: Dict[str, Any] = {
        "program": spec.name,
        "key": key,
        "cache_hit": hit,
        "status": "compiled",
        "lower_ms": None,
        "compile_ms": None,
        "memory": None,
        "error": None,
    }
    t0 = time.perf_counter()
    try:
        with tracer.span("aot_compile", program=spec.name, hit=hit):
            lowered = spec.fn.lower(*spec.args, **spec.kwargs)
            report["lower_ms"] = round((time.perf_counter() - t0) * 1000.0, 1)
            if footprint_sink is not None:
                try:
                    footprint_sink(spec, lowered.as_text())
                except Exception as e:  # noqa: BLE001 — the footprint is
                    # advisory: losing it must never cost the warmup
                    if verbose:
                        print(f"aot: WARNING — footprint sink failed for "
                              f"{spec.name}: {type(e).__name__}: {e}")
            t1 = time.perf_counter()
            compiled = lowered.compile()
            report["compile_ms"] = round((time.perf_counter() - t1) * 1000.0, 1)
    except Exception as e:  # noqa: BLE001 — per-program isolation IS the contract
        # e.g. this container's protobuf pipeline-compile crash: warn, move on
        report["status"] = "failed"
        report["error"] = f"{type(e).__name__}: {str(e)[:300]}"
        if report["compile_ms"] is None:
            report["compile_ms"] = round((time.perf_counter() - t0) * 1000.0, 1)
        if verbose:
            print(f"aot: WARNING — {spec.name} failed to compile "
                  f"({report['error']}); continuing the sweep")
        return report
    report["memory"] = memory_stats(compiled)
    if store is not None and key is not None:
        try:
            store.record_compile(
                key,
                program=spec.name,
                compile_ms=report["compile_ms"],
                hit=hit,
                meta={"family": spec.meta.get("family")},
            )
            if serialize and not hit:
                report["serialized"] = store.save_executable(key, compiled)
        except Exception as e:  # noqa: BLE001 — manifest is advisory: losing
            # it costs accounting, never correctness (and never the sweep)
            report["manifest_error"] = f"{type(e).__name__}: {str(e)[:300]}"
            if verbose:
                print(f"aot: WARNING — {spec.name} compiled but the manifest "
                      f"write failed ({report['manifest_error']}); continuing")
    if verbose:
        mem = report["memory"]
        mem_s = f", peak {mem['total_mb']:.0f} MB" if mem else ""
        print(
            f"aot: {spec.name}: {'hit' if hit else 'miss'}, "
            f"lower {report['lower_ms']:.0f} ms, "
            f"compile {report['compile_ms']:.0f} ms{mem_s}"
        )
    return report


def warmup_programs(
    specs: Sequence[aot_registry.ProgramSpec],
    store: Optional[aot_cache.ArtifactStore] = None,
    *,
    plan: Any = None,
    model_cfg: Any = None,
    serialize: bool = False,
    verbose: bool = True,
    footprint_sink: Any = None,
) -> List[Dict[str, Any]]:
    """Compile every spec (failure-isolated); one report per program."""
    from galvatron_tpu.obs.tracing import tracer

    with tracer.span("aot_warmup", programs=len(specs)):
        return [
            compile_program(
                s, store, plan=plan, model_cfg=model_cfg,
                serialize=serialize, verbose=verbose,
                footprint_sink=footprint_sink,
            )
            for s in specs
        ]


def warmup_plan(
    cfg,
    hp,
    *,
    global_bsz: int,
    seq_len: Optional[int] = None,
    store: Optional[aot_cache.ArtifactStore] = None,
    include: Optional[Sequence[str]] = None,
    num_slots: int = 4,
    prefill_chunk: int = 32,
    kv_block_size: int = 16,
    kv_num_blocks: int = 0,
    serve_quant: str = "off",
    spec_decode_k: int = 0,
    adam: Any = None,
    serialize: bool = False,
    verbose: bool = True,
    footprint_sink: Any = None,
) -> List[Dict[str, Any]]:
    """Warm every registered program of one (plan × model × live mesh):
    enumerate from the registry, compile each, attach the GTA015 analytic
    memory prediction to the train_step report for the cross-check."""
    import jax

    ctx = aot_registry.ProgramContext(
        cfg=cfg, hp=hp, global_bsz=global_bsz, seq_len=seq_len,
        num_slots=num_slots, prefill_chunk=prefill_chunk, adam=adam,
        kv_block_size=kv_block_size, kv_num_blocks=kv_num_blocks,
        serve_quant=serve_quant, spec_decode_k=spec_decode_k,
    )
    try:
        specs = aot_registry.enumerate_programs(ctx, include=include)
    except Exception as e:  # noqa: BLE001 — an unbuildable family must not abort
        if verbose:
            print(f"aot: WARNING — program enumeration failed: "
                  f"{type(e).__name__}: {str(e)[:300]}")
        return [{
            "program": "<enumerate>", "key": None, "cache_hit": False,
            "status": "failed", "lower_ms": None, "compile_ms": None,
            "memory": None, "error": f"{type(e).__name__}: {str(e)[:300]}",
        }]
    reports = warmup_programs(
        specs, store, plan=hp, model_cfg=cfg, serialize=serialize,
        verbose=verbose, footprint_sink=footprint_sink,
    )
    pred = (
        predicted_train_memory_mb(cfg, hp, jax.device_count(), global_bsz)
        if hp is not None
        else None
    )
    if pred is not None:
        for r in reports:
            if r["program"] == "train_step":
                r["predicted_train_mb"] = pred
                mem = r.get("memory")
                if mem and mem.get("total_mb"):
                    r["predicted_over_compiled"] = round(
                        pred / mem["total_mb"], 3
                    )
    return reports


def warmup_runtime(
    rt,
    global_bsz: int,
    seq_len: int,
    *,
    store: Optional[aot_cache.ArtifactStore] = None,
    plan: Any = None,
    model_cfg: Any = None,
    include: Optional[Sequence[str]] = None,
    verbose: bool = True,
) -> List[Dict[str, Any]]:
    """Trainer-startup warmup over an ALREADY-BUILT runtime (no second
    ``build_runtime``): compile the programs the run will dispatch so the
    loop's first step pays a persistent-cache deserialize, not an XLA
    compile, and the manifest tells the watchdog whether this start was
    warm.  ``include`` narrows to specific programs (the trainer passes the
    ones its own path will actually call); default = the whole family."""
    ctx = aot_registry.ProgramContext(
        cfg=rt.cfg, hp=rt.hp, global_bsz=global_bsz, seq_len=seq_len,
        mesh=rt.mesh, axes=rt.axes, runtime=rt,
    )
    specs = aot_registry.enumerate_programs(
        ctx, include=include if include is not None else ("trainer",)
    )
    return warmup_programs(
        specs, store,
        plan=plan if plan is not None else rt.hp,
        model_cfg=model_cfg if model_cfg is not None else rt.cfg,
        verbose=verbose,
    )


def summarize(reports: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "programs": len(reports),
        "compiled": sum(1 for r in reports if r["status"] == "compiled"),
        "failed": sum(1 for r in reports if r["status"] == "failed"),
        # hits/misses partition the COMPILED programs (compiled = hits +
        # misses, programs = compiled + failed): a key known to the manifest
        # whose program fails THIS sweep is a failure, not a hit — nothing
        # got warm
        "hits": sum(
            1 for r in reports if r["status"] == "compiled" and r.get("cache_hit")
        ),
        "misses": sum(
            1 for r in reports if r["status"] == "compiled" and not r.get("cache_hit")
        ),
        "total_compile_ms": round(
            sum(r["compile_ms"] or 0.0 for r in reports), 1
        ),
    }


def write_report(path: str, reports: Sequence[Dict[str, Any]]) -> None:
    """JSONL: one record per program + one trailing summary record."""
    with open(path, "w") as f:
        for r in reports:
            f.write(json.dumps(r) + "\n")
        f.write(json.dumps({"summary": summarize(reports)}) + "\n")
