"""Plan-keyed persistent compile-artifact cache.

Galvatron's premise is that the plan is known *before* the run — so the
compiled programs a (plan × model shape × mesh) run needs are a pure
function of inputs that exist with no data and no devices warmed up.  This
module makes compilation a first-class, keyed artifact:

- :func:`enable_persistent_cache` — the ONE shared wiring of JAX's
  persistent compilation cache (previously hand-wired three divergent ways:
  tests/conftest.py, a CI env block, and nothing at all for the trainer).
  Idempotent, and by default it respects a cache dir that is already
  configured (conftest, ``JAX_COMPILATION_CACHE_DIR``) instead of silently
  redirecting the process-wide cache mid-run.
- :func:`program_key` — the content key of one compiled program:
  ``(program name, plan_hash, effective model-shape dict, topology
  fingerprint, jax/jaxlib version, relevant XLA flags, donate/sharding
  signature of the abstract inputs)``.  Any semantic change to any term
  forces a miss; identical inputs hash identically across processes and
  hosts (the same property ``core/strategy.plan_hash`` gives plans).
- :class:`ArtifactStore` — a managed manifest over the JAX cache dir:
  atomically-committed JSON (tmp+fsync+rename, ``core/retry.py`` — the
  checkpoint/shard-manifest idioms) recording per-key compile_ms,
  hit/miss/invalidation accounting, and optionally the ``serialize``\\d AOT
  executable itself where the backend supports it.  The manifest is what
  lets a *restart* know its programs are warm before compiling anything —
  the watchdog's first-step grace and the elastic prewarm both key on it.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, Iterable, Optional

AOT_SCHEMA = "galvatron-aot-v1"
MANIFEST_NAME = "galvatron_aot_manifest.json"

#: environment variables whose value changes the compiled artifact
RELEVANT_XLA_ENV = ("XLA_FLAGS", "LIBTPU_INIT_ARGS")

_DISABLED_VALUES = ("0", "off", "none", "disabled")


# ---------------------------------------------------------------------------
# shared persistent-cache wiring
# ---------------------------------------------------------------------------


def enable_persistent_cache(
    cache_dir: Optional[str],
    *,
    min_entry_bytes: Optional[int] = None,
    min_compile_time_s: Optional[float] = None,
    override: bool = False,
) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns the EFFECTIVE cache dir: when one is already configured (a
    conftest, an operator's ``JAX_COMPILATION_CACHE_DIR``) and ``override``
    is False, the existing dir is kept and returned — a derived default must
    never silently redirect a process-wide cache that someone wired on
    purpose.  ``override=True`` (an explicit ``--compile_cache_dir``)
    redirects, dropping jax's in-process cache handle so the new dir takes
    effect even after compiles already happened.

    Thresholds: a redirect wires ``min_entry_bytes``/``min_compile_time_s``
    (0 / 0.0 when unspecified, so every compile persists); when the dir is
    already wired exactly here, only EXPLICITLY passed thresholds land — a
    bare re-enable of the live dir (trainer consult, elastic prewarm) must
    not silently drop a conftest's write-churn floor mid-suite, but a caller
    that asks for a floor gets it even when the dir came from the
    environment."""
    import jax

    current = getattr(jax.config, "jax_compilation_cache_dir", None)

    def _apply_thresholds(entry_default=None, time_default=None):
        eb = min_entry_bytes if min_entry_bytes is not None else entry_default
        ct = min_compile_time_s if min_compile_time_s is not None else time_default
        if eb is not None:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", int(eb))
        if ct is not None:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", float(ct)
            )

    if cache_dir is None:
        return current
    cache_dir = os.path.abspath(cache_dir)
    if current and os.path.abspath(current) == cache_dir:
        _apply_thresholds()
        return current
    if current and not override:
        return current
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    _apply_thresholds(0, 0.0)
    # jax latches its cache state (including "no cache configured") on the
    # FIRST compile of the process; a config update after that is silently
    # ignored until the module handle is dropped. Private API, so
    # best-effort by contract — entries on disk are untouched.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — older/newer jax: keep the config update
        pass
    return cache_dir


def resolve_compile_cache_dir(ns) -> Optional[str]:
    """The run's compile-cache dir, by precedence: explicit
    ``--compile_cache_dir`` (``0``/``off``/``none`` disables) > the
    ``JAX_COMPILATION_CACHE_DIR`` env > a dir already configured on
    ``jax.config`` > the ``.jax_cache`` sibling of ``--save`` > None."""
    v = getattr(ns, "compile_cache_dir", None)
    if v:
        if str(v).lower() in _DISABLED_VALUES:
            return None
        return os.path.abspath(v)
    env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env:
        return os.path.abspath(env)
    try:
        import jax

        current = getattr(jax.config, "jax_compilation_cache_dir", None)
    except Exception:  # pragma: no cover — jax is always importable here
        current = None
    if current:
        return os.path.abspath(current)
    save = getattr(ns, "save", None)
    if save:
        return os.path.join(os.path.dirname(os.path.abspath(save)), ".jax_cache")
    return None


# ---------------------------------------------------------------------------
# program keys
# ---------------------------------------------------------------------------


def topology_fingerprint(devices=None) -> Dict[str, Any]:
    """Compile-relevant topology identity: platform, device kind, device and
    process counts.  (The mesh SHAPE rides the sharding signature — two
    plans on the same chips with different meshes already key apart.)"""
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    d0 = devices[0]
    return {
        "platform": str(getattr(d0, "platform", "unknown")),
        "device_kind": str(getattr(d0, "device_kind", "unknown")),
        "device_count": len(devices),
        "process_count": int(jax.process_count()),
    }


def xla_flag_signature(env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """The XLA-relevant env flags, token-sorted and de-duplicated so neither
    reordering a flag string nor stating the same token twice (a launcher's
    XLA_FLAGS + `--force_world`'s append of the identical flag) masquerades
    as a different compiler configuration."""
    env = os.environ if env is None else env
    out: Dict[str, Any] = {}
    for var in RELEVANT_XLA_ENV:
        v = env.get(var)
        out[var] = sorted(set(v.split())) if v else None
    return out


def jax_version_string() -> str:
    import jax

    try:
        import jaxlib

        return f"{jax.__version__}/{jaxlib.__version__}"
    except Exception:  # pragma: no cover
        return str(jax.__version__)


def abstract_signature(args: Any, kwargs: Optional[Dict[str, Any]] = None) -> str:
    """Digest of the flattened abstract inputs: shape, dtype and sharding of
    every leaf (non-array leaves — static configs — by repr).  Shardings are
    part of the compiled artifact's identity: the same shapes under a
    different partitioning are a different program."""
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    h.update(str(treedef).encode())
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sharding = getattr(leaf, "sharding", None)
            h.update(
                f"{tuple(shape)}|{getattr(leaf, 'dtype', None)}|{sharding}".encode()
            )
        else:
            h.update(repr(leaf).encode())
    return h.hexdigest()


def program_key(
    name: str,
    *,
    plan: Any = None,
    model_cfg: Any = None,
    abstract_args: Any = (),
    abstract_kwargs: Optional[Dict[str, Any]] = None,
    donate: Any = None,
    topology: Optional[Dict[str, Any]] = None,
    xla_flags: Optional[Dict[str, Any]] = None,
    jax_version: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Stable content key of one compiled program (see module docstring for
    the term list).  ``plan`` is a HybridParallelConfig / strategy JSON dict
    (hashed through :func:`core.strategy.plan_hash`, so provenance keys and
    ordering never matter) or ``None`` for plan-free programs (serving,
    generate)."""
    payload: Dict[str, Any] = {
        "schema": AOT_SCHEMA,
        "program": str(name),
        "plan_hash": None,
        "model_shape": None,
        "topology": topology if topology is not None else topology_fingerprint(),
        "jax": jax_version if jax_version is not None else jax_version_string(),
        "xla_flags": xla_flags if xla_flags is not None else xla_flag_signature(),
        "args_sig": abstract_signature(abstract_args, abstract_kwargs),
        "donate": repr(donate) if donate is not None else None,
        "extra": extra or None,
    }
    if plan is not None:
        from galvatron_tpu.core.strategy import plan_hash

        payload["plan_hash"] = plan if isinstance(plan, str) else plan_hash(plan)
    if model_cfg is not None:
        from galvatron_tpu.analysis.plan_check import model_shape_dict

        payload["model_shape"] = model_shape_dict(model_cfg)
        # executed-config terms the shape dict cannot see: a different
        # kernel, compute dtype, or packing contract is a different program
        payload["model_exec"] = {
            k: str(getattr(model_cfg, k, None))
            for k in ("attn_impl", "dtype", "pack_sequences", "mlp_recompute")
        }
    digest = hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=repr).encode()
    ).hexdigest()
    return f"aot:{digest}"


# ---------------------------------------------------------------------------
# manifest store
# ---------------------------------------------------------------------------


class ArtifactStore:
    """Managed manifest over a persistent-compile-cache directory.

    One JSON file (``galvatron_aot_manifest.json``) maps program keys to
    accounting records; commits are atomic (tmp + fsync + rename + dir
    fsync) and retried through ``core/retry.py`` — the same idioms as the
    checkpoint and shard manifests, because the same partial-write failure
    modes apply.  The store is advisory: losing it costs accounting, never
    correctness (JAX's own cache still serves the executables)."""

    def __init__(self, cache_dir: str):
        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.manifest_path = os.path.join(self.dir, MANIFEST_NAME)
        # per-store-instance session accounting (the manifest carries the
        # cross-process totals)
        self.hits = 0
        self.misses = 0
        # the parsed manifest, read once per store instance: a warmup sweep
        # of P programs against a long-lived cache dir must not pay P full
        # JSON parses of an ever-growing file. Writes go through the cached
        # doc, so the last writer wins the whole file — the same window the
        # per-call read-modify-write had, and the store is advisory by
        # contract (losing accounting never loses executables).
        self._doc: Optional[Dict[str, Any]] = None

    # -- manifest I/O --------------------------------------------------------

    def _load(self) -> Dict[str, Any]:
        if self._doc is None:
            self._doc = self._read()
        return self._doc

    def _read(self) -> Dict[str, Any]:
        from galvatron_tpu.core.retry import with_retries

        if not os.path.exists(self.manifest_path):
            return {"schema": AOT_SCHEMA, "programs": {}, "invalidations": 0}

        def read():
            with open(self.manifest_path) as f:
                return json.load(f)

        try:
            doc = with_retries(read, describe=f"read {self.manifest_path}")
        except (OSError, ValueError) as e:
            # a torn manifest must not take the run down — accounting
            # restarts; the executables in jax's cache are untouched
            print(f"aot: unreadable manifest {self.manifest_path} ({e!r}); resetting")
            return {"schema": AOT_SCHEMA, "programs": {}, "invalidations": 0}
        if not isinstance(doc, dict) or not isinstance(doc.get("programs"), dict):
            return {"schema": AOT_SCHEMA, "programs": {}, "invalidations": 0}
        doc.setdefault("invalidations", 0)
        return doc

    def _write(self, doc: Dict[str, Any]) -> None:
        from galvatron_tpu.core.retry import with_retries

        tmp = self.manifest_path + f".tmp.{os.getpid()}"

        def commit():
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.manifest_path)
            try:
                fd = os.open(self.dir, os.O_RDONLY)
            except OSError:
                return  # not all filesystems expose dir fds
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

        try:
            with_retries(commit, describe=f"commit {self.manifest_path}")
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    # -- accounting ----------------------------------------------------------

    def entries(self) -> Dict[str, Any]:
        return self._load()["programs"]

    def lookup(self, key: str) -> Optional[Dict[str, Any]]:
        return self._load()["programs"].get(key)

    def record_compile(
        self,
        key: str,
        *,
        program: str,
        compile_ms: float,
        hit: bool,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Account one compile of ``key``: a key already present is a HIT
        (the persistent cache served it), a new key is a MISS (real XLA
        compile, now cached for every later process)."""
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        doc = self._load()
        entry = doc["programs"].setdefault(
            key,
            {
                "program": program,
                "first_compiled_at": time.time(),
                "compiles": 0,
                "hits": 0,
                "first_compile_ms": round(float(compile_ms), 3),
            },
        )
        entry["program"] = program
        entry["compiles"] = int(entry.get("compiles", 0)) + 1
        if hit:
            entry["hits"] = int(entry.get("hits", 0)) + 1
        entry["last_compile_ms"] = round(float(compile_ms), 3)
        entry["last_compiled_at"] = time.time()
        if meta:
            entry.setdefault("meta", {}).update(meta)
        self._write(doc)
        return entry

    def invalidate(self, keys: Optional[Iterable[str]] = None) -> int:
        """Drop manifest entries (all of them by default) and count the
        invalidation — an operator clearing a poisoned cache, or a test
        forcing misses.  Serialized executables for dropped keys are removed
        too; JAX's own cache files are left alone (they are content-addressed
        and harmless)."""
        doc = self._load()
        dropped = list(doc["programs"]) if keys is None else [
            k for k in keys if k in doc["programs"]
        ]
        for k in dropped:
            del doc["programs"][k]
            exe = self._exec_path(k)
            if os.path.exists(exe):
                try:
                    os.remove(exe)
                except OSError:
                    pass
        doc["invalidations"] = int(doc.get("invalidations", 0)) + len(dropped)
        self._write(doc)
        return len(dropped)

    def stats(self) -> Dict[str, Any]:
        doc = self._load()
        progs = doc["programs"]
        return {
            "entries": len(progs),
            "session_hits": self.hits,
            "session_misses": self.misses,
            "total_compiles": sum(int(e.get("compiles", 0)) for e in progs.values()),
            "total_hits": sum(int(e.get("hits", 0)) for e in progs.values()),
            "invalidations": int(doc.get("invalidations", 0)),
        }

    # -- serialized executables ---------------------------------------------

    def _exec_path(self, key: str) -> str:
        return os.path.join(self.dir, key.replace(":", "_") + ".exec")

    def save_executable(self, key: str, compiled) -> bool:
        """Persist the AOT executable itself (``jax.experimental.
        serialize_executable``) where the backend supports it.  Best-effort
        by contract: any failure returns False and costs nothing — the
        persistent compile cache remains the durable layer.  The blob is
        VERIFIED reloadable before it is recorded: some backends serialize
        happily but cannot reload the result (e.g. CPU executables that
        were themselves deserialized from the compile cache reference
        jit'd symbols) — ``serialized: true`` must mean loadable."""
        import pickle

        try:
            from jax.experimental import serialize_executable as se

            blob = pickle.dumps(se.serialize(compiled))
        except Exception:  # noqa: BLE001 — backend/tree not serializable here
            return False
        path = self._exec_path(key)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        if self.load_executable(key) is None:
            try:
                os.remove(path)
            except OSError:
                pass
            return False
        doc = self._load()
        if key in doc["programs"]:
            doc["programs"][key]["serialized"] = True
            doc["programs"][key]["serialized_bytes"] = len(blob)
            self._write(doc)
        return True

    def load_executable(self, key: str):
        """Deserialize a previously saved executable, or None.  The caller
        owns validity: the key already encodes everything that could make a
        stale executable unsafe (topology, versions, flags, shardings)."""
        import pickle

        path = self._exec_path(key)
        if not os.path.exists(path):
            return None
        try:
            from jax.experimental import serialize_executable as se

            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:  # noqa: BLE001 — a corrupt blob degrades to recompile
            return None
