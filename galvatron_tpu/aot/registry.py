"""Program registry: every jitted entry point a run needs, from shapes alone.

Engines register their jitted entry points together with *abstract-input
builders*, so the full set of programs a (plan × ModelConfig × mesh) run
will compile is enumerable with no data and no device work:

- ``trainer`` (parallel/hybrid.py): ``train_step`` / ``eval_loss`` /
  ``init_state`` — one family covering the GSPMD hybrid engine AND the
  gpipe / 1F1B / interleaved / enc-dec / swin stage programs, because every
  pipeline runtime compiles through the same jitted ``train_step`` entry
  (`build_runtime` dispatches; the registry does not care which engine won).
- ``serving`` (serving/engine.py): ``serving_prefill`` / ``serving_decode``
  — the engine's declared pinned programs at its static shapes — or the
  paged twins ``serving_paged_prefill`` / ``serving_paged_decode`` when the
  context carries ``kv_num_blocks != 0``; plus ``serving_decode_verify``
  (and its paged twin) at ``(num_slots, 1+k)`` when ``spec_decode_k > 0``,
  and int8 params avals + a ``serve_quant`` key term when quantized.
- ``generate`` (registered here, lazily importing models/generation):
  the batch eval/generate program at its default length bucket.

A builder takes a :class:`ProgramContext` and returns a list of
:class:`ProgramSpec` — the jitted callable plus the abstract
(``jax.ShapeDtypeStruct``/``eval_shape``) arguments to ``lower`` it with.
Builders may decline (return ``[]``) when the context does not apply (a
non-causal model has no serving programs).  `aot/warmup.py` turns specs
into compiled artifacts; `aot/cache.py` turns them into keys.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Builder = Callable[["ProgramContext"], List["ProgramSpec"]]


@dataclass
class ProgramContext:
    """Everything a builder may need, shapes only — no arrays, no devices."""

    cfg: Any  # models.modeling.ModelConfig (effective/executed config)
    hp: Any = None  # core.strategy.HybridParallelConfig; None = plan-free only
    global_bsz: int = 8
    seq_len: Optional[int] = None  # None = cfg.sample_len
    mesh: Any = None  # pre-built Mesh/axes (trainer); None = build from hp
    axes: Any = None
    runtime: Any = None  # an already-built HybridParallelRuntime to reuse
    adam: Any = None  # core.optim.AdamConfig; None = build_runtime's default
    # serving shapes (Engine ctor defaults)
    num_slots: int = 4
    prefill_chunk: int = 32
    max_seq_len: Optional[int] = None
    # paged-KV serving shapes: kv_num_blocks 0 = slot backend (contiguous
    # cache, serving_prefill/serving_decode), != 0 = paged backend
    # (serving_paged_prefill/serving_paged_decode; -1 sizes the pool to the
    # slot cache's HBM footprint)
    kv_block_size: int = 16
    kv_num_blocks: int = 0
    # serving numerics/speed levers that change the program set: int8
    # weights change every serving program's params avals (and add an
    # explicit key_extra term); spec_decode_k > 0 adds the decode_verify
    # program at (num_slots, 1+k)
    serve_quant: str = "off"
    spec_decode_k: int = 0
    # generate shapes
    max_new_tokens: int = 32
    length_bucket: int = 64


@dataclass
class ProgramSpec:
    """One AOT-lowerable program: ``fn.lower(*args, **kwargs)`` must be
    legal with every leaf of ``args``/``kwargs`` abstract (static jit args
    ride along concrete).  ``meta`` carries the key terms the avals cannot
    express (donation, family, engine notes)."""

    name: str
    fn: Any
    args: Tuple[Any, ...] = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    meta: Dict[str, Any] = field(default_factory=dict)


_BUILDERS: Dict[str, Tuple[Builder, bool, Tuple[str, ...]]] = {}


def register_program(
    name: str,
    builder: Builder,
    *,
    needs_plan: bool = False,
    programs: Sequence[str] = (),
) -> None:
    """Register (or replace — re-imports are idempotent) a program family.
    ``needs_plan=True`` families are skipped when the context has no
    hybrid-parallel plan (plan-free warmups: serving cold-start).
    ``programs`` names the specs the builder can emit, so an ``include``
    filter can skip a family without paying its builder."""
    _BUILDERS[name] = (builder, bool(needs_plan), tuple(programs))


def registered_families() -> List[str]:
    _ensure_engines_imported()
    return sorted(_BUILDERS)


def _ensure_engines_imported() -> None:
    """Importing an engine module registers its family (decentralized
    registration keeps the jitted entry points and their abstract-input
    builders in the file that owns the shapes)."""
    import galvatron_tpu.parallel.hybrid  # noqa: F401 — registers 'trainer'
    import galvatron_tpu.serving.engine  # noqa: F401 — registers 'serving'


def enumerate_programs(
    ctx: ProgramContext, include: Optional[Sequence[str]] = None
) -> List[ProgramSpec]:
    """All ProgramSpecs the registered engines would compile for ``ctx``.

    ``include`` filters by family OR program name (``["serving"]`` and
    ``["serving_decode"]`` both work).  Enumeration never compiles: specs
    hold jitted callables + abstract inputs only."""
    _ensure_engines_imported()
    want = set(include) if include else None
    specs: List[ProgramSpec] = []
    for family in sorted(_BUILDERS):
        builder, needs_plan, names = _BUILDERS[family]
        if needs_plan and ctx.hp is None:
            continue
        if want is not None and family not in want and names and not (set(names) & want):
            continue  # the filter cannot match anything this family emits
        built = builder(ctx)
        for s in built:
            s.meta.setdefault("family", family)
        specs.extend(
            built if want is None
            else (s for s in built if family in want or s.name in want)
        )
    return specs


# ---------------------------------------------------------------------------
# the plan-free 'generate' family (models/generation.py owns no registry
# import of its own — generation is a leaf module the serving engine also
# imports, so its family is declared here against the lazy import)
# ---------------------------------------------------------------------------


def _generate_builder(ctx: ProgramContext) -> List[ProgramSpec]:
    import jax
    import jax.numpy as jnp

    cfg = ctx.cfg
    if not getattr(cfg, "causal", True) or getattr(cfg, "objective", "clm") != "clm" \
            or getattr(cfg, "enc_layers", 0) > 0:
        return []  # generation requires a decoder-only causal LM
    from galvatron_tpu.models import generation, modeling

    params_abs = jax.eval_shape(
        lambda k: modeling.init_model_params(k, cfg), jax.random.key(0)
    )
    p_len = min(ctx.length_bucket, cfg.max_seq_len)
    prompt = jax.ShapeDtypeStruct((1, p_len), jnp.int32)
    lengths = jax.ShapeDtypeStruct((1,), jnp.int32)
    key = jax.eval_shape(lambda: jax.random.key(0))
    max_new = min(ctx.max_new_tokens, max(1, cfg.max_seq_len - p_len))
    return [
        ProgramSpec(
            "generate",
            generation.generate,
            (params_abs, prompt, lengths, cfg, key),
            {"max_new_tokens": max_new, "min_prompt_len": 1},
            meta={"family": "generate", "engine": "generation.generate"},
        )
    ]


register_program(
    "generate", _generate_builder, needs_plan=False, programs=("generate",)
)
