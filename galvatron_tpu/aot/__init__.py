"""AOT compile subsystem: plan-keyed warmup + persistent compile artifacts.

The plan is known before the run — so the programs the run needs are
enumerable (`registry.py`), their compiled artifacts are keyable and
persistable (`cache.py`), and cold-start/restart downtime becomes a cache
lookup (`warmup.py`, ``cli warmup``, the trainer's startup consult, the
elastic child's re-plan prewarm, the serving engine's warm start).
"""

from galvatron_tpu.aot.cache import (  # noqa: F401
    ArtifactStore,
    enable_persistent_cache,
    program_key,
    resolve_compile_cache_dir,
)
from galvatron_tpu.aot.registry import (  # noqa: F401
    ProgramContext,
    ProgramSpec,
    enumerate_programs,
    register_program,
)
