"""Minimal REST text-generation server.

Counterpart of the reference's Flask server (reference:
galvatron/site_package/megatron/text_generation_server.py — PUT /api with
{"prompts": [...], "tokens_to_generate": N, ...}). Stdlib-only
(http.server) so it carries no extra dependencies.

Two execution paths behind one API:

- **Continuous-batching engine** (``serving.Engine``, the default from the
  CLI): each prompt is submitted as a request and resolved via a future;
  overlapping requests share every decode iteration over one persistent
  slot-based KV cache instead of queueing on a lock. Backpressure is the
  engine's bounded admission queue (``QueueFull``/TTL expiry → 503).
- **Serialized legacy path** (``engine=None``): ``generate_np`` under the
  global service lock, pending work bounded by the ``max_pending`` gate
  (excess requests fail fast with 503). Kept as the compatible single-shot
  path and as the baseline ``bench_serving.py`` measures against.

API (POST or PUT /api, JSON body):
  {"prompts": ["..."], "tokens_to_generate": 32, "temperature": 0.0,
   "top_k": 0, "top_p": 0.0}
→ {"text": ["...completions..."], "tokens": [[...ids...]]}
GET /healthz → {"status": "ok", "uptime_s": ..., "requests": {succeeded/
                failed/rejected}, "gate" | "serving": saturation + engine
                stats, "model": {vocab/hidden/layers/heads/max_seq_len}}
GET /metrics → the same stats in Prometheus text exposition (obs/prom.py):
               request counters, engine counters, TTFT quantiles, occupancy,
               HBM gauges — a scraper target next to the probe.
POST /profile?steps=N (or JSON {"steps": N, "timeout_s": S, "dir": ...})
             → on-demand jax.profiler capture over the next N engine decode
               iterations (obs/flight.capture_profile); 409 while another
               capture runs, 503 where the backend lacks xprof support.

Connections are handled on threads — /healthz answers while generations are
in flight — and each carries a socket timeout (``request_timeout_s``) so a
stalled client (connected but never sending, or trickling a body) releases
its thread instead of accumulating forever. Replies into sockets the client
already abandoned (BrokenPipeError/ConnectionResetError) are swallowed and
the connection closed, like the stalled-read TimeoutError path — a
disconnecting client must not leave tracebacks or a half-written 500.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import jax

from galvatron_tpu.utils.metrics import Counters


class _Gate:
    """Bounded pending-work gate for the legacy path, with visible
    saturation (capacity/in_use/rejected land in /healthz so a 503-storm
    shows up on the probe, not just client-side)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._sem = threading.BoundedSemaphore(capacity)
        self._lock = threading.Lock()
        self.in_use = 0
        self.rejected = 0

    def acquire(self) -> bool:
        ok = self._sem.acquire(blocking=False)
        with self._lock:
            if ok:
                self.in_use += 1
            else:
                self.rejected += 1
        return ok

    def release(self) -> None:
        with self._lock:
            self.in_use -= 1
        self._sem.release()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "in_use": self.in_use,
                "saturated": self.in_use >= self.capacity,
                "rejected": self.rejected,
            }


class ServiceBusy(RuntimeError):
    """Mapped to HTTP 503 by the handler (queue full / TTL expired)."""


class GenerationService:
    def __init__(self, params, cfg, tokenizer, max_new_default: int = 64,
                 seed: int = 0, engine=None):
        self.params = params
        self.cfg = cfg
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self.key = jax.random.key(seed)
        self.engine = engine  # serving.Engine, or None for the legacy path
        self.lock = threading.Lock()
        self.started_at = time.time()
        self.counters = Counters("succeeded", "failed", "rejected")
        self.gate: Optional[_Gate] = None  # set by run_server (legacy path)
        # one capture at a time: jax.profiler state is process-global
        self._profile_lock = threading.Lock()

    @property
    def requests_served(self) -> int:
        # back-compat alias (pre-engine probes read this): completed OK
        return self.counters.get("succeeded")

    def health(self) -> dict:
        c = self.cfg
        req = self.counters.snapshot()
        out = {
            "status": "ok",
            "uptime_s": round(time.time() - self.started_at, 3),
            "requests_served": req["succeeded"],
            "requests": req,
            "model": {
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden_size,
                "num_layers": c.num_layers,
                "num_heads": c.num_heads,
                "max_seq_len": c.max_seq_len,
            },
        }
        if self.gate is not None:
            out["gate"] = self.gate.snapshot()
        if self.engine is not None:
            out["serving"] = self.engine.stats()
        return out

    def _validate(self, body: dict):
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        prompts = body.get("prompts")
        if not isinstance(prompts, list) or not prompts or not all(
            isinstance(p, str) for p in prompts
        ):
            raise ValueError("'prompts' must be a non-empty list of strings")
        n_new = int(body.get("tokens_to_generate", self.max_new_default))
        if n_new < 0 or n_new > self.cfg.max_seq_len:
            raise ValueError(f"tokens_to_generate out of range [0, {self.cfg.max_seq_len}]")
        return prompts, n_new

    def generate(self, body: dict) -> dict:
        prompts, n_new = self._validate(body)
        tok_prompts = [self.tok.encode(p) for p in prompts]
        if self.engine is not None:
            outs = self._generate_engine(body, tok_prompts, n_new)
        else:
            outs = self._generate_serialized(body, tok_prompts, n_new)
        texts = [self.tok.decode(o[len(tp):]) for o, tp in zip(outs, tok_prompts)]
        return {"text": texts, "tokens": outs}

    def _generate_engine(self, body: dict, tok_prompts, n_new: int):
        """Continuous-batching path: one engine request per prompt, futures
        resolved as slots retire. Prompts of one HTTP request overlap with
        each other AND with every other in-flight connection."""
        from concurrent.futures import TimeoutError as FuturesTimeout

        from galvatron_tpu.serving import QueueFull, RequestExpired

        ttl = body.get("ttl_s")
        futures = []
        try:
            for tp in tok_prompts:
                futures.append(self.engine.submit(
                    tp, n_new,
                    temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 0.0)),
                    ttl_s=float(ttl) if ttl is not None else None,
                ))
            return [f.result(timeout=self.engine.result_timeout_s)
                    for f in futures]
        except QueueFull as e:
            raise ServiceBusy(str(e)) from e
        except RequestExpired as e:
            raise ServiceBusy(str(e)) from e
        except FuturesTimeout as e:
            # distinct from the socket-read TimeoutError the handler treats
            # as a dead client: this request must get a real 500 and count
            # as failed (on 3.11+ FuturesTimeout aliases TimeoutError, which
            # the handler's stalled-client branch would silently swallow)
            raise RuntimeError(
                f"generation timed out after {self.engine.result_timeout_s}s"
            ) from e
        finally:
            # failed or abandoned siblings must not burn chip time: cancel
            # whatever has not been admitted yet (done futures ignore it)
            for f in futures:
                f.cancel()

    def profile_capture(self, steps: int, trace_dir: Optional[str] = None,
                        timeout_s: float = 30.0) -> dict:
        """On-demand jax.profiler window over the next ``steps`` engine decode
        iterations (POST /profile). Raises ``ValueError`` for usage errors,
        ``ServiceBusy`` when a capture is already running, ``RuntimeError``
        when the backend has no xprof support (→ 503, not a crash)."""
        if self.engine is None:
            raise ValueError(
                "on-demand profiling needs the continuous-batching engine "
                "(--num_slots > 0): captures are bounded by decode iterations"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        # clamp client-supplied bounds: the capture holds the PROCESS-GLOBAL
        # jax.profiler plus a handler thread, and every concurrent /profile
        # 409s until it ends — an unbounded steps/timeout_s would let one
        # request pin both for as long as it likes
        steps = min(steps, 10_000)
        timeout_s = min(max(float(timeout_s), 1.0), 300.0)
        if not self._profile_lock.acquire(blocking=False):
            raise ServiceBusy("a profiler capture is already in progress")
        try:
            import tempfile

            from galvatron_tpu.obs.flight import capture_profile

            return capture_profile(
                trace_dir or tempfile.mkdtemp(prefix="galvatron_profile_"),
                steps,
                lambda: self.engine.counters.get("steps"),
                timeout_s=timeout_s,
            )
        finally:
            self._profile_lock.release()

    def _generate_serialized(self, body: dict, tok_prompts, n_new: int):
        """Legacy single-shot path: full prefill+decode per request under
        the global lock (generation holds the chip anyway)."""
        from galvatron_tpu.models import generation

        with self.lock:
            self.key, sub = jax.random.split(self.key)
            return generation.generate_np(
                self.params,
                self.cfg,
                tok_prompts,
                max_new_tokens=n_new,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 0.0)),
                eos_id=self.tok.eos_id if self.tok.eos_id is not None else -1,
                pad_id=self.tok.pad_id if self.tok.pad_id is not None else 0,
                key=sub,
            )


def _make_handler(service: GenerationService, request_timeout_s: float):
    class Handler(BaseHTTPRequestHandler):
        # socketserver per-connection timeout: applied to the socket in
        # setup(), so a stalled read (request line or body) raises instead
        # of pinning its handler thread forever
        timeout = request_timeout_s

        def _reply(self, code: int, payload: dict):
            self._reply_raw(code, json.dumps(payload).encode(), "application/json")

        def _reply_raw(self, code: int, data: bytes, ctype: str):
            # a client that disconnected mid-generation must not blow a
            # traceback out of the handler (nor can the 500-path itself be
            # allowed to throw) — drop the dead connection like the
            # stalled-read TimeoutError path does
            try:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError, TimeoutError, OSError):
                self.close_connection = True

        def _handle(self):
            route, _, query = self.path.partition("?")
            route = route.rstrip("/")
            if route == "/profile":
                return self._do_profile(query)
            if route != "/api":
                return self._reply(404, {"error": "use /api"})
            # bounded pending work (legacy path only): the threading server
            # gives every connection a thread, and a thread parked on the
            # generation lock is NOT covered by the socket timeout — without
            # the gate, a slow generation plus a request flood accumulates
            # unbounded threads and then burns chip time generating for
            # clients long gone. Saturated → fail fast with 503 (/healthz
            # stays open). With the engine, admission control lives in the
            # scheduler's bounded queue instead (QueueFull/TTL → 503).
            gate = service.gate
            if gate is not None and not gate.acquire():
                service.counters.inc("rejected")
                return self._reply(
                    503, {"error": "server busy: too many pending requests"}
                )
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                resp = service.generate(body)
                service.counters.inc("succeeded")
                return self._reply(200, resp)
            except TimeoutError:
                # stalled client mid-body: drop the connection without
                # attempting to write a reply into the dead socket
                self.close_connection = True
                return
            except ServiceBusy as e:
                service.counters.inc("rejected")
                return self._reply(503, {"error": str(e)})
            except ValueError as e:
                service.counters.inc("failed")
                return self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                service.counters.inc("failed")
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                if gate is not None:
                    gate.release()

        def _do_profile(self, query: str):
            """POST /profile — bounded on-demand profiler capture."""
            from urllib.parse import parse_qs

            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("request body must be a JSON object")
                qs = parse_qs(query)
                steps = body.get("steps", qs.get("steps", [1])[0])
                timeout_s = body.get("timeout_s", qs.get("timeout_s", [30.0])[0])
                return self._reply(200, service.profile_capture(
                    steps, trace_dir=body.get("dir"), timeout_s=float(timeout_s)
                ))
            except TimeoutError:
                self.close_connection = True
                return
            except ServiceBusy as e:
                return self._reply(409, {"error": str(e)})
            except ValueError as e:
                return self._reply(400, {"error": str(e)})
            except RuntimeError as e:
                # no xprof on this backend: an honest 503, not a traceback
                return self._reply(503, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        do_POST = _handle
        do_PUT = _handle

        def do_GET(self):
            route = self.path.partition("?")[0].rstrip("/")
            if route == "/healthz":
                return self._reply(200, service.health())
            if route == "/metrics":
                from galvatron_tpu.obs.prom import CONTENT_TYPE, server_metrics_text

                try:
                    text = server_metrics_text(service)
                except Exception as e:  # noqa: BLE001 — scrape must not kill serving
                    return self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return self._reply_raw(200, text.encode(), CONTENT_TYPE)
            return self._reply(
                404,
                {"error": "use /api (POST/PUT), /healthz, /metrics (GET), "
                          "or /profile (POST)"},
            )

        def log_message(self, *a):  # quiet
            pass

    return Handler


def run_server(service: GenerationService, port: int = 5000, host: str = "127.0.0.1",
               ready_event: Optional[threading.Event] = None,
               request_timeout_s: float = 120.0, max_pending: int = 8) -> None:
    # threading server: /healthz must answer while a long generation is in
    # flight — a probe timing out against a busy single-threaded server
    # would get a healthy process restarted. On the legacy path max_pending
    # bounds queued /api work (excess → 503); with the engine, the
    # scheduler's bounded queue is the admission control.
    if service.engine is None:
        service.gate = _Gate(max_pending)
    httpd = ThreadingHTTPServer(
        (host, port), _make_handler(service, request_timeout_s)
    )
    service.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    print(f"generation server listening on http://{host}:{httpd.server_address[1]}/api")
    httpd.serve_forever()
