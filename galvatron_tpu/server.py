"""Minimal REST text-generation server.

Counterpart of the reference's Flask server (reference:
galvatron/site_package/megatron/text_generation_server.py — PUT /api with
{"prompts": [...], "tokens_to_generate": N, ...}). Stdlib-only
(http.server) so it carries no extra dependencies; single worker, requests
are served sequentially in arrival order (generation holds the chip anyway).

API (POST or PUT /api, JSON body):
  {"prompts": ["..."], "tokens_to_generate": 32, "temperature": 0.0,
   "top_k": 0, "top_p": 0.0}
→ {"text": ["...completions..."], "tokens": [[...ids...]]}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Optional

import jax


class GenerationService:
    def __init__(self, params, cfg, tokenizer, max_new_default: int = 64, seed: int = 0):
        self.params = params
        self.cfg = cfg
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self.key = jax.random.key(seed)
        self.lock = threading.Lock()

    def generate(self, body: dict) -> dict:
        from galvatron_tpu.models import generation

        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        prompts = body.get("prompts")
        if not isinstance(prompts, list) or not prompts or not all(
            isinstance(p, str) for p in prompts
        ):
            raise ValueError("'prompts' must be a non-empty list of strings")
        n_new = int(body.get("tokens_to_generate", self.max_new_default))
        if n_new < 0 or n_new > self.cfg.max_seq_len:
            raise ValueError(f"tokens_to_generate out of range [0, {self.cfg.max_seq_len}]")
        tok_prompts = [self.tok.encode(p) for p in prompts]
        with self.lock:
            self.key, sub = jax.random.split(self.key)
            outs = generation.generate_np(
                self.params,
                self.cfg,
                tok_prompts,
                max_new_tokens=n_new,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 0.0)),
                eos_id=self.tok.eos_id if self.tok.eos_id is not None else -1,
                pad_id=self.tok.pad_id if self.tok.pad_id is not None else 0,
                key=sub,
            )
        texts = [self.tok.decode(o[len(tp):]) for o, tp in zip(outs, tok_prompts)]
        return {"text": texts, "tokens": outs}


def _make_handler(service: GenerationService):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _handle(self):
            if self.path.rstrip("/") != "/api":
                return self._reply(404, {"error": "use /api"})
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                return self._reply(200, service.generate(body))
            except ValueError as e:
                return self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        do_POST = _handle
        do_PUT = _handle

        def log_message(self, *a):  # quiet
            pass

    return Handler


def run_server(service: GenerationService, port: int = 5000, host: str = "127.0.0.1",
               ready_event: Optional[threading.Event] = None) -> None:
    httpd = HTTPServer((host, port), _make_handler(service))
    service.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    print(f"generation server listening on http://{host}:{httpd.server_address[1]}/api")
    httpd.serve_forever()
