"""Minimal REST text-generation server.

Counterpart of the reference's Flask server (reference:
galvatron/site_package/megatron/text_generation_server.py — PUT /api with
{"prompts": [...], "tokens_to_generate": N, ...}). Stdlib-only
(http.server) so it carries no extra dependencies.

Two execution paths behind one API:

- **Continuous-batching engine** (``serving.Engine``, the default from the
  CLI): each prompt is submitted as a request and resolved via a future;
  overlapping requests share every decode iteration over one persistent
  slot-based KV cache instead of queueing on a lock. Backpressure is the
  engine's bounded admission queue (``QueueFull``/TTL expiry → 503).
- **Serialized legacy path** (``engine=None``): ``generate_np`` under the
  global service lock, pending work bounded by the ``max_pending`` gate
  (excess requests fail fast with 503). Kept as the compatible single-shot
  path and as the baseline ``bench_serving.py`` measures against.

API (POST or PUT /api, JSON body):
  {"prompts": ["..."], "tokens_to_generate": 32, "temperature": 0.0,
   "top_k": 0, "top_p": 0.0}
→ {"text": ["...completions..."], "tokens": [[...ids...]]}
GET /healthz → {"status": "ok" | "draining", "uptime_s": ..., "requests":
                {succeeded/failed/rejected/cancelled}, "gate" | "serving":
                saturation + engine stats, "model": {vocab/hidden/layers/
                heads/max_seq_len}}
GET /readyz  → 200 {"ready": true} while accepting traffic; 503 the moment
               a drain begins (or the engine gives up restarting) — a load
               balancer stops routing BEFORE the last in-flight token lands
POST /drain  → begin a graceful drain (same as SIGTERM): admission closes
               (new /api requests 503 + Retry-After), queued requests are
               shed, in-flight slots run to completion under
               --drain_timeout_s, then the server stops and exits 0
GET /metrics → the same stats in Prometheus text exposition (obs/prom.py):
               request counters, engine counters, TTFT quantiles, occupancy,
               HBM gauges — a scraper target next to the probe.
POST /profile?steps=N (or JSON {"steps": N, "timeout_s": S, "dir": ...})
             → on-demand jax.profiler capture over the next N engine decode
               iterations (obs/flight.capture_profile); 409 while another
               capture runs, 503 where the backend lacks xprof support.

Connections are handled on threads — /healthz answers while generations are
in flight — and each carries a socket timeout (``request_timeout_s``) so a
stalled client (connected but never sending, or trickling a body) releases
its thread instead of accumulating forever. Replies into sockets the client
already abandoned (BrokenPipeError/ConnectionResetError) are swallowed and
the connection closed, like the stalled-read TimeoutError path — a
disconnecting client must not leave tracebacks or a half-written 500.
"""

from __future__ import annotations

import json
import select
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

import jax

from galvatron_tpu.core import faults
from galvatron_tpu.obs.tracing import tracer as _obs_tracer
from galvatron_tpu.utils.metrics import Counters


class _Gate:
    """Bounded pending-work gate for the legacy path, with visible
    saturation (capacity/in_use/rejected land in /healthz so a 503-storm
    shows up on the probe, not just client-side)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._sem = threading.BoundedSemaphore(capacity)
        self._lock = threading.Lock()
        self.in_use = 0
        self.rejected = 0

    def acquire(self) -> bool:
        ok = self._sem.acquire(blocking=False)
        with self._lock:
            if ok:
                self.in_use += 1
            else:
                self.rejected += 1
        return ok

    def release(self) -> None:
        with self._lock:
            self.in_use -= 1
        self._sem.release()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "in_use": self.in_use,
                "saturated": self.in_use >= self.capacity,
                "rejected": self.rejected,
            }


class ServiceBusy(RuntimeError):
    """Mapped to HTTP 503 by the handler (queue full / TTL expired / drain /
    engine restart). ``detail`` lands in the JSON body so clients and the
    chaos harness can tell the causes apart; ``retry_after_s`` becomes a
    ``Retry-After`` header (draining: come back after the drain window)."""

    def __init__(self, msg: str, detail: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.detail = detail
        self.retry_after_s = retry_after_s


class ClientDisconnected(RuntimeError):
    """The handler's disconnect poll saw the client vanish mid-generation:
    the requests were cancelled, nobody is listening — drop the connection
    without writing a reply."""


class GenerationService:
    def __init__(self, params, cfg, tokenizer, max_new_default: int = 64,
                 seed: int = 0, engine=None):
        self.params = params
        self.cfg = cfg
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self.key = jax.random.key(seed)
        self.engine = engine  # serving.Engine, or None for the legacy path
        self.lock = threading.Lock()
        self.started_at = time.time()
        self.counters = Counters("succeeded", "failed", "rejected", "cancelled")
        self.gate: Optional[_Gate] = None  # set by run_server (legacy path)
        # one capture at a time: jax.profiler state is process-global
        self._profile_lock = threading.Lock()
        # SLO burn-rate engine (obs/slo.py), armed by cli serve wiring; when
        # None the service runs SLO-less with zero added work
        self.slo = None
        # graceful drain state (begin_drain): admission closes, /readyz goes
        # unready immediately, in-flight work completes under the deadline
        self.draining = False
        self.drain_timeout_s = 30.0
        self._drain_lock = threading.Lock()
        self._drained = threading.Event()
        # startup readiness gate (cli serve sets it, then clears it once the
        # engine's warm start AND a first real generation have completed):
        # a router/load-balancer watching /readyz must never dispatch into a
        # replica still paying cold compile. /api stays open while starting
        # — a direct client just shares the compile, exactly the lazy path.
        self.starting = False

    @property
    def ready(self) -> bool:
        """What ``/readyz`` keys on: accepting NEW work. Unready while the
        engine is still warming (``starting``), the moment a drain begins
        (in-flight work may still be finishing — that is the point: the
        load balancer stops routing before the last token lands), and when
        the engine is dead (crash-restart budget exhausted)."""
        if self.starting or self.draining:
            return False
        if self.engine is not None and not self.engine.alive:
            return False
        return True

    def begin_drain(self, reason: str = "drain") -> dict:
        """Graceful drain, blocking until drained (or the deadline): shed
        the queue, let in-flight slots finish, close the engine. Idempotent
        — a second caller (SIGTERM after POST /drain) waits for the first
        drain to finish. Returns the engine's post-drain audit."""
        with self._drain_lock:
            first = not self.draining
            self.draining = True
        if not first:
            self._drained.wait(timeout=self.drain_timeout_s + 10.0)
            return getattr(self, "drain_audit", {})
        _obs_tracer.instant("serving_drain_begin", reason=reason)
        if self.engine is not None:
            # close admission at the ENGINE first so racing submissions
            # refuse with EngineDraining even before handlers see the flag
            self.engine.begin_drain()
            audit = self.engine.drain(self.drain_timeout_s)
        else:
            # legacy path: the gate stops admitting (handler checks
            # `draining`); wait for in-flight generations to release it
            deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < deadline:
                if self.gate is None or self.gate.snapshot()["in_use"] == 0:
                    break
                time.sleep(0.02)
            g = self.gate.snapshot() if self.gate is not None else {}
            audit = {"leaked": bool(g.get("in_use")), **g}
        self.drain_audit = audit
        _obs_tracer.instant("serving_drain_done", reason=reason,
                            leaked=audit.get("leaked"))
        self._drained.set()
        return audit

    @property
    def requests_served(self) -> int:
        # back-compat alias (pre-engine probes read this): completed OK
        return self.counters.get("succeeded")

    def health(self) -> dict:
        c = self.cfg
        req = self.counters.snapshot()
        out = {
            "status": ("draining" if self.draining
                       else "starting" if self.starting else "ok"),
            "ready": self.ready,
            "uptime_s": round(time.time() - self.started_at, 3),
            "requests_served": req["succeeded"],
            "requests": req,
            "model": {
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden_size,
                "num_layers": c.num_layers,
                "num_heads": c.num_heads,
                "max_seq_len": c.max_seq_len,
            },
        }
        if self.gate is not None:
            out["gate"] = self.gate.snapshot()
        if self.engine is not None:
            out["serving"] = self.engine.stats()
        # SLO degradation is part of health, not just /metrics: a load
        # balancer's probe sees WHY the replica is degraded without scraping
        # (empty list = no rule in breach; absent only when no SLO is armed)
        if self.slo is not None:
            out["degraded_reasons"] = self.slo.degraded_reasons()
        return out

    def _validate(self, body: dict):
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        prompts = body.get("prompts")
        if not isinstance(prompts, list) or not prompts or not all(
            isinstance(p, str) for p in prompts
        ):
            raise ValueError("'prompts' must be a non-empty list of strings")
        n_new = int(body.get("tokens_to_generate", self.max_new_default))
        if n_new < 0 or n_new > self.cfg.max_seq_len:
            raise ValueError(f"tokens_to_generate out of range [0, {self.cfg.max_seq_len}]")
        return prompts, n_new

    def generate(self, body: dict,
                 disconnect_check: Optional[Callable[[], bool]] = None,
                 trace_id: Optional[str] = None) -> dict:
        prompts, n_new = self._validate(body)
        tok_prompts = [self.tok.encode(p) for p in prompts]
        if self.engine is not None:
            outs, truncated = self._generate_engine(
                body, tok_prompts, n_new, disconnect_check, trace_id=trace_id
            )
        else:
            outs = self._generate_serialized(body, tok_prompts, n_new)
            truncated = [None] * len(outs)
        texts = [self.tok.decode(o[len(tp):]) for o, tp in zip(outs, tok_prompts)]
        resp = {"text": texts, "tokens": outs}
        if any(truncated):
            # deadline_policy=partial: the row stopped at its deadline —
            # say so instead of passing truncation off as a completion
            resp["truncated"] = truncated
        return resp

    def _generate_engine(self, body: dict, tok_prompts, n_new: int,
                         disconnect_check: Optional[Callable[[], bool]] = None,
                         trace_id: Optional[str] = None):
        """Continuous-batching path: one engine request per prompt, futures
        resolved as slots retire. Prompts of one HTTP request overlap with
        each other AND with every other in-flight connection. While the
        futures are pending, ``disconnect_check`` polls the client socket —
        a vanished client cancels its requests at the next decode iteration
        (the slot frees) instead of burning chip time to completion."""
        from concurrent.futures import FIRST_EXCEPTION
        from concurrent.futures import TimeoutError as FuturesTimeout
        from concurrent.futures import wait as futures_wait

        from galvatron_tpu.serving import (
            DeadlineExceeded,
            EngineClosed,
            EngineDraining,
            EngineRestarted,
            QueueFull,
            RequestExpired,
            RequestShed,
        )

        ttl = body.get("ttl_s")
        reqs = []
        try:
            for tp in tok_prompts:
                reqs.append(self.engine.submit_request(
                    tp, n_new,
                    temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 0.0)),
                    ttl_s=float(ttl) if ttl is not None else None,
                    trace_id=trace_id,
                ))
            deadline = time.monotonic() + self.engine.result_timeout_s
            pending = {r.future for r in reqs}
            while pending:
                done, pending = futures_wait(
                    pending, timeout=0.05, return_when=FIRST_EXCEPTION
                )
                if done and any(f.exception() is not None for f in done):
                    break  # propagate via .result() below
                if not pending:
                    break
                if disconnect_check is not None and disconnect_check():
                    for r in reqs:
                        r.cancel("disconnect")
                    self.counters.inc("cancelled")
                    raise ClientDisconnected(
                        "client vanished mid-generation; requests cancelled"
                    )
                if time.monotonic() > deadline:
                    raise FuturesTimeout()
            outs = [r.future.result(timeout=self.engine.result_timeout_s)
                    for r in reqs]
            truncated = [r.finish_reason if r.finish_reason == "deadline"
                         else None for r in reqs]
            if self.slo is not None:
                # per-request SLO samples (obs/slo.py): success is an
                # availability "good"; a deadline-truncated row is a miss;
                # TTFT is the observed first-token latency
                for r in reqs:
                    self.slo.observe("availability", bad=False)
                    self.slo.observe("deadline_miss_ratio",
                                     bad=r.finish_reason == "deadline",
                                     rid=r.rid)
                    if r.first_token_at is not None:
                        self.slo.observe_latency(
                            "ttft_p99", r.first_token_at - r.submitted_at,
                            rid=r.rid)
            return outs, truncated
        except QueueFull as e:
            # paged admission may leave the head request queued until blocks
            # free up, so queue-full 503s carry a Retry-After hint sized to
            # the engine's backlog horizon (chaos `evict` asserts the header)
            raise ServiceBusy(
                str(e), detail="queue_full",
                retry_after_s=getattr(self.engine, "busy_retry_after_s", None),
            ) from e
        except (RequestExpired, DeadlineExceeded) as e:
            if self.slo is not None:
                self.slo.observe("deadline_miss_ratio", bad=True)
            raise ServiceBusy(str(e), detail="expired") from e
        except RequestShed as e:
            raise ServiceBusy(str(e), detail="shed") from e
        except EngineDraining as e:
            raise ServiceBusy(str(e), detail="draining",
                              retry_after_s=e.retry_after_s) from e
        except EngineRestarted as e:
            # Retry-After like draining 503s: the supervisor's own backoff
            # delay says when the recovered engine will be looping again
            if self.slo is not None:
                self.slo.observe("availability", bad=True,
                                 reason="engine_restarted")
            raise ServiceBusy(str(e), detail="engine_restarted",
                              retry_after_s=e.retry_after_s) from e
        except EngineClosed as e:
            if self.slo is not None:
                self.slo.observe("availability", bad=True,
                                 reason="engine_closed")
            raise ServiceBusy(str(e), detail="engine_closed") from e
        except FuturesTimeout as e:
            # distinct from the socket-read TimeoutError the handler treats
            # as a dead client: this request must get a real 500 and count
            # as failed (on 3.11+ FuturesTimeout aliases TimeoutError, which
            # the handler's stalled-client branch would silently swallow)
            if self.slo is not None:
                self.slo.observe("availability", bad=True, reason="timeout")
            raise RuntimeError(
                f"generation timed out after {self.engine.result_timeout_s}s"
            ) from e
        finally:
            # failed or abandoned siblings must not burn chip time: cancel
            # whatever has not completed (done futures ignore it; admitted
            # requests retire at the next decode iteration)
            for r in reqs:
                r.cancel("abandoned")
                r.future.cancel()

    def profile_capture(self, steps: int, trace_dir: Optional[str] = None,
                        timeout_s: float = 30.0) -> dict:
        """On-demand jax.profiler window over the next ``steps`` engine decode
        iterations (POST /profile). Raises ``ValueError`` for usage errors,
        ``ServiceBusy`` when a capture is already running, ``RuntimeError``
        when the backend has no xprof support (→ 503, not a crash)."""
        if self.engine is None:
            raise ValueError(
                "on-demand profiling needs the continuous-batching engine "
                "(--num_slots > 0): captures are bounded by decode iterations"
            )
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        # clamp client-supplied bounds: the capture holds the PROCESS-GLOBAL
        # jax.profiler plus a handler thread, and every concurrent /profile
        # 409s until it ends — an unbounded steps/timeout_s would let one
        # request pin both for as long as it likes
        steps = min(steps, 10_000)
        timeout_s = min(max(float(timeout_s), 1.0), 300.0)
        if not self._profile_lock.acquire(blocking=False):
            raise ServiceBusy("a profiler capture is already in progress")
        try:
            import tempfile

            from galvatron_tpu.obs.flight import capture_profile

            return capture_profile(
                trace_dir or tempfile.mkdtemp(prefix="galvatron_profile_"),
                steps,
                lambda: self.engine.counters.get("steps"),
                timeout_s=timeout_s,
            )
        finally:
            self._profile_lock.release()

    def _generate_serialized(self, body: dict, tok_prompts, n_new: int):
        """Legacy single-shot path: full prefill+decode per request under
        the global lock (generation holds the chip anyway)."""
        from galvatron_tpu.models import generation

        with self.lock:
            self.key, sub = jax.random.split(self.key)
            return generation.generate_np(
                self.params,
                self.cfg,
                tok_prompts,
                max_new_tokens=n_new,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 0.0)),
                eos_id=self.tok.eos_id if self.tok.eos_id is not None else -1,
                pad_id=self.tok.pad_id if self.tok.pad_id is not None else 0,
                key=sub,
            )


def _make_handler(service: GenerationService, request_timeout_s: float):
    class Handler(BaseHTTPRequestHandler):
        # socketserver per-connection timeout: applied to the socket in
        # setup(), so a stalled read (request line or body) raises instead
        # of pinning its handler thread forever
        timeout = request_timeout_s

        def _reply(self, code: int, payload: dict, headers: Optional[dict] = None):
            self._reply_raw(code, json.dumps(payload).encode(),
                            "application/json", headers)

        def _reply_raw(self, code: int, data: bytes, ctype: str,
                       headers: Optional[dict] = None):
            # a client that disconnected mid-generation must not blow a
            # traceback out of the handler (nor can the 500-path itself be
            # allowed to throw) — drop the dead connection like the
            # stalled-read TimeoutError path does
            try:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError, TimeoutError, OSError):
                self.close_connection = True

        def _client_disconnected(self) -> bool:
            """Is the client still on the other end? The request body was
            already read in full, so any readable-with-zero-bytes on the
            socket is the client's FIN (a clean close); a reset raises.
            ``client_stall`` (core/faults.py) simulates a vanished client
            for the chaos harness without a real socket reset."""
            if faults.maybe_client_stall():
                return True
            try:
                r, _, _ = select.select([self.connection], [], [], 0)
                if not r:
                    return False
                return self.connection.recv(1, socket.MSG_PEEK) == b""
            except OSError:
                return True

        def _handle(self):
            route, _, query = self.path.partition("?")
            route = route.rstrip("/")
            if route == "/drain":
                # admin endpoint, same lifecycle as SIGTERM: reply first
                # (the drain outlives this connection), then drain + stop
                # on a separate thread — serve_forever returns once the
                # in-flight work has landed
                threading.Thread(
                    target=drain_and_stop, args=(service, "POST /drain"),
                    daemon=True,
                ).start()
                return self._reply(200, {
                    "status": "draining",
                    "drain_timeout_s": service.drain_timeout_s,
                })
            if route == "/profile":
                return self._do_profile(query)
            if route != "/api":
                return self._reply(404, {"error": "use /api or /drain"})
            if service.draining:
                # admission gate is closed: fail fast with an honest 503 and
                # a Retry-After so a well-behaved client backs off while the
                # load balancer (watching /readyz) reroutes
                service.counters.inc("rejected")
                return self._reply(
                    503,
                    {"error": "server draining", "detail": "draining"},
                    headers={"Retry-After":
                             str(max(1, int(service.drain_timeout_s)))},
                )
            # bounded pending work (legacy path only): the threading server
            # gives every connection a thread, and a thread parked on the
            # generation lock is NOT covered by the socket timeout — without
            # the gate, a slow generation plus a request flood accumulates
            # unbounded threads and then burns chip time generating for
            # clients long gone. Saturated → fail fast with 503 (/healthz
            # stays open). With the engine, admission control lives in the
            # scheduler's bounded queue instead (QueueFull/TTL → 503).
            gate = service.gate
            if gate is not None and not gate.acquire():
                service.counters.inc("rejected")
                return self._reply(
                    503, {"error": "server busy: too many pending requests"}
                )
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                # the fleet router's correlation id (obs/correlate.py):
                # present only when the router runs with tracing armed —
                # absent header ⇒ trace_id None ⇒ zero extra work
                from galvatron_tpu.obs.correlate import TRACE_HEADER

                resp = service.generate(
                    body, disconnect_check=self._client_disconnected,
                    trace_id=self.headers.get(TRACE_HEADER),
                )
                service.counters.inc("succeeded")
                return self._reply(200, resp)
            except TimeoutError:
                # stalled client mid-body: drop the connection without
                # attempting to write a reply into the dead socket
                self.close_connection = True
                return
            except ClientDisconnected:
                # the disconnect poll cancelled the requests (already
                # counted); nobody is listening for a reply
                self.close_connection = True
                return
            except ServiceBusy as e:
                service.counters.inc("rejected")
                payload = {"error": str(e)}
                if e.detail:
                    payload["detail"] = e.detail
                headers = None
                if e.retry_after_s is not None:
                    headers = {"Retry-After": str(max(1, int(e.retry_after_s)))}
                return self._reply(503, payload, headers)
            except ValueError as e:
                service.counters.inc("failed")
                return self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                service.counters.inc("failed")
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                if gate is not None:
                    gate.release()

        def _do_profile(self, query: str):
            """POST /profile — bounded on-demand profiler capture."""
            from urllib.parse import parse_qs

            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("request body must be a JSON object")
                qs = parse_qs(query)
                steps = body.get("steps", qs.get("steps", [1])[0])
                timeout_s = body.get("timeout_s", qs.get("timeout_s", [30.0])[0])
                return self._reply(200, service.profile_capture(
                    steps, trace_dir=body.get("dir"), timeout_s=float(timeout_s)
                ))
            except TimeoutError:
                self.close_connection = True
                return
            except ServiceBusy as e:
                return self._reply(409, {"error": str(e)})
            except ValueError as e:
                return self._reply(400, {"error": str(e)})
            except RuntimeError as e:
                # no xprof on this backend: an honest 503, not a traceback
                return self._reply(503, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        do_POST = _handle
        do_PUT = _handle

        def do_GET(self):
            route = self.path.partition("?")[0].rstrip("/")
            if route == "/healthz":
                # liveness: 200 even while draining — the process is healthy,
                # it is READINESS that flipped (status says "draining")
                return self._reply(200, service.health())
            if route == "/readyz":
                if service.ready:
                    return self._reply(200, {"ready": True})
                return self._reply(503, {
                    "ready": False,
                    "status": ("draining" if service.draining
                               else "starting" if service.starting
                               else "engine_dead"),
                })
            if route == "/metrics":
                from galvatron_tpu.obs.prom import CONTENT_TYPE, server_metrics_text

                try:
                    text = server_metrics_text(service)
                except Exception as e:  # noqa: BLE001 — scrape must not kill serving
                    return self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return self._reply_raw(200, text.encode(), CONTENT_TYPE)
            return self._reply(
                404,
                {"error": "use /api (POST/PUT), /healthz, /readyz, /metrics "
                          "(GET), or /profile, /drain (POST)"},
            )

        def log_message(self, *a):  # quiet
            pass

    return Handler


def drain_and_stop(service: GenerationService, reason: str) -> dict:
    """The zero-downtime shutdown sequence (SIGTERM and ``POST /drain``):
    ``begin_drain`` (admission closes, ``/readyz`` unready, queue shed,
    in-flight completes under ``drain_timeout_s``, engine closes with a
    zero-leak audit), then stop ``serve_forever`` so the process exits 0."""
    audit = service.begin_drain(reason=reason)
    httpd = getattr(service, "httpd", None)
    if httpd is not None:
        httpd.shutdown()
    return audit


def run_server(service: GenerationService, port: int = 5000, host: str = "127.0.0.1",
               ready_event: Optional[threading.Event] = None,
               request_timeout_s: float = 120.0, max_pending: int = 8,
               drain_timeout_s: float = 30.0) -> None:
    # threading server: /healthz must answer while a long generation is in
    # flight — a probe timing out against a busy single-threaded server
    # would get a healthy process restarted. On the legacy path max_pending
    # bounds queued /api work (excess → 503); with the engine, the
    # scheduler's bounded queue is the admission control.
    if service.engine is None:
        service.gate = _Gate(max_pending)
    service.drain_timeout_s = float(drain_timeout_s)
    httpd = ThreadingHTTPServer(
        (host, port), _make_handler(service, request_timeout_s)
    )
    service.httpd = httpd
    # SIGTERM = graceful drain (zero-downtime shutdown), not an abort: the
    # handler only installs from the main thread (tests run run_server on a
    # worker thread and drive POST /drain instead). The drain runs on its
    # own thread — a signal handler must not block for the drain window.
    try:
        signal.signal(
            signal.SIGTERM,
            lambda signum, frame: threading.Thread(
                target=drain_and_stop, args=(service, f"signal {signum}"),
                daemon=True,
            ).start(),
        )
    except ValueError:
        pass  # not the main thread
    if ready_event is not None:
        ready_event.set()
    print(f"generation server listening on http://{host}:{httpd.server_address[1]}/api")
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
    if service.draining:
        audit = getattr(service, "drain_audit", {})
        print(f"server drained: leaked={audit.get('leaked')} "
              f"audit={json.dumps(audit)}")
