"""Minimal REST text-generation server.

Counterpart of the reference's Flask server (reference:
galvatron/site_package/megatron/text_generation_server.py — PUT /api with
{"prompts": [...], "tokens_to_generate": N, ...}). Stdlib-only
(http.server) so it carries no extra dependencies; generation requests are
serialized by the service lock (generation holds the chip anyway).

API (POST or PUT /api, JSON body):
  {"prompts": ["..."], "tokens_to_generate": 32, "temperature": 0.0,
   "top_k": 0, "top_p": 0.0}
→ {"text": ["...completions..."], "tokens": [[...ids...]]}
GET /healthz → {"status": "ok", "uptime_s": ..., "requests_served": ...,
                "model": {vocab/hidden/layers/heads/max_seq_len}}

Connections are handled on threads — generation itself stays serialized by
the service lock, but /healthz answers while a generation is in flight —
and each carries a socket timeout (``request_timeout_s``) so a stalled
client (connected but never sending, or trickling a body) releases its
thread instead of accumulating forever. Pending /api work is bounded by
``max_pending`` (excess requests fail fast with 503 instead of queueing
threads on the generation lock for clients that may be long gone).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import jax


class GenerationService:
    def __init__(self, params, cfg, tokenizer, max_new_default: int = 64, seed: int = 0):
        self.params = params
        self.cfg = cfg
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self.key = jax.random.key(seed)
        self.lock = threading.Lock()
        self.started_at = time.time()
        self.requests_served = 0

    def health(self) -> dict:
        c = self.cfg
        return {
            "status": "ok",
            "uptime_s": round(time.time() - self.started_at, 3),
            "requests_served": self.requests_served,
            "model": {
                "vocab_size": c.vocab_size,
                "hidden_size": c.hidden_size,
                "num_layers": c.num_layers,
                "num_heads": c.num_heads,
                "max_seq_len": c.max_seq_len,
            },
        }

    def generate(self, body: dict) -> dict:
        from galvatron_tpu.models import generation

        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        prompts = body.get("prompts")
        if not isinstance(prompts, list) or not prompts or not all(
            isinstance(p, str) for p in prompts
        ):
            raise ValueError("'prompts' must be a non-empty list of strings")
        n_new = int(body.get("tokens_to_generate", self.max_new_default))
        if n_new < 0 or n_new > self.cfg.max_seq_len:
            raise ValueError(f"tokens_to_generate out of range [0, {self.cfg.max_seq_len}]")
        tok_prompts = [self.tok.encode(p) for p in prompts]
        with self.lock:
            self.key, sub = jax.random.split(self.key)
            outs = generation.generate_np(
                self.params,
                self.cfg,
                tok_prompts,
                max_new_tokens=n_new,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 0.0)),
                eos_id=self.tok.eos_id if self.tok.eos_id is not None else -1,
                pad_id=self.tok.pad_id if self.tok.pad_id is not None else 0,
                key=sub,
            )
            # counted inside the generation lock: re-acquiring it afterwards
            # would park this finished request behind the next generation
            self.requests_served += 1
        texts = [self.tok.decode(o[len(tp):]) for o, tp in zip(outs, tok_prompts)]
        return {"text": texts, "tokens": outs}


def _make_handler(
    service: GenerationService, request_timeout_s: float,
    gate: threading.BoundedSemaphore,
):
    class Handler(BaseHTTPRequestHandler):
        # socketserver per-connection timeout: applied to the socket in
        # setup(), so a stalled read (request line or body) raises instead
        # of pinning its handler thread forever
        timeout = request_timeout_s

        def _reply(self, code: int, payload: dict):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _handle(self):
            if self.path.rstrip("/") != "/api":
                return self._reply(404, {"error": "use /api"})
            # bounded pending work: the threading server gives every
            # connection a thread, and a thread parked on the generation
            # lock is NOT covered by the socket timeout — without the gate,
            # a slow generation plus a request flood accumulates unbounded
            # threads and then burns chip time generating for clients long
            # gone. Saturated → fail fast with 503 (/healthz stays open).
            if not gate.acquire(blocking=False):
                return self._reply(
                    503, {"error": "server busy: too many pending requests"}
                )
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                return self._reply(200, service.generate(body))
            except TimeoutError:
                # stalled client mid-body: drop the connection without
                # attempting to write a reply into the dead socket
                self.close_connection = True
                return
            except ValueError as e:
                return self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface to client
                return self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            finally:
                gate.release()

        do_POST = _handle
        do_PUT = _handle

        def do_GET(self):
            if self.path.rstrip("/") == "/healthz":
                return self._reply(200, service.health())
            return self._reply(404, {"error": "use /api (POST/PUT) or /healthz (GET)"})

        def log_message(self, *a):  # quiet
            pass

    return Handler


def run_server(service: GenerationService, port: int = 5000, host: str = "127.0.0.1",
               ready_event: Optional[threading.Event] = None,
               request_timeout_s: float = 120.0, max_pending: int = 8) -> None:
    # threading server: generation is serialized by service.lock anyway, but
    # /healthz must answer while a long generation is in flight — a probe
    # timing out against a busy single-threaded server would get a healthy
    # process restarted. max_pending bounds queued /api work (excess → 503).
    gate = threading.BoundedSemaphore(max_pending)
    httpd = ThreadingHTTPServer(
        (host, port), _make_handler(service, request_timeout_s, gate)
    )
    service.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    print(f"generation server listening on http://{host}:{httpd.server_address[1]}/api")
    httpd.serve_forever()
